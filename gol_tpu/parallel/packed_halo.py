"""Bit-packed row-strip sharding with ring halo exchange — SWAR stepping
(ops/bitlife.py) composed with the ICI ring (parallel/halo.py).

Each device owns a strip of H/n rows stored packed (strip_rows/32 word
rows x W columns of uint32). Per turn each shard ppermutes its edge
*word rows* to its ring neighbours, then steps with the same carry-save
adder as the single-chip packed path, with the cross-word vertical
carries sourced from the halo words at the strip edges. The per-turn
message is a whole 32-row word-row (4W bytes) even though the
single-turn step only consumes its boundary bit — deliberately: the
word-row is exactly the ghost the 32-turn deep blocks below consume in
full, one uint32 lane array needs no repacking on either side, and at
these sizes ring transfers are latency-bound, not byte-bound (a 512-
wide edge is 2 KB). Per-turn mode costs 4x the dense path's bytes; the
deep path repays it 32x over.

The torus closes because the ring does: shard 0's upper neighbour is
shard n-1 (ref spec: README.md:239-245 — the halo-exchange extension the
reference never implemented; here it is packed as well as distributed).

Communication-avoiding deep halos: a ghost word-row is 32 complete
rows, and the stencil corrupts validity inward by only one row per
turn — so after ONE exchange of each edge word-row, a shard can step
its ghost-extended block 32 turns locally and slice the exact strip
back out. `step_n` uses these blocks whenever it can, cutting ring
collectives 32x-128x vs the per-turn exchange (the classic
communication-avoiding stencil, done with the packing's own geometry;
per-turn stepping remains for diffs and turn remainders). The extended
block is stepped with the plain toroidal kernel: its vertical wrap only
touches rows whose validity the shrink analysis already wrote off.

On TPU the local block stepping runs the VMEM-resident pallas kernel
(ops/pallas_bitlife.py) with a 4-word ghost slab per side — one
ppermute pair buys 128 exact local turns AND the local turns go at the
single-chip fast-path rate instead of the XLA fori_loop rate. Where
the extended block misses the kernel's tile alignment or VMEM budget
(or off-TPU), the XLA one-word-ghost blocks remain the path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.models.rules import Rule
from gol_tpu.ops import bitlife
from gol_tpu.ops.bitlife import WORD
from gol_tpu.parallel import partition
from gol_tpu.parallel.halo import (
    AXIS,
    cpu_serializing_sync,
    edge_exchange,
    ring_perms,
)


def packable_sharded(height: int, shards: int) -> bool:
    """Each strip must be a whole number of words."""
    return (
        shards > 0
        and height % shards == 0
        and (height // shards) % WORD == 0
    )


def packable_sharded_uneven(height: int, shards: int) -> bool:
    """The word-granular balanced split: when the word-rows do not
    divide the shard count, shards can still own ceil/floor whole
    word-rows each (e.g. 512² over 3 shards = 6/5/5 words) and keep
    the SWAR ring + deep halos — every shard just needs at least one
    whole word (VERDICT r4 Missing #1: non-divisor counts were
    correct-but-second-class on the dense ring). Divisor counts are
    excluded on purpose: they are the even ring's territory
    (`packable_sharded`), and the balanced constructors reject them
    rather than run a degenerate split whose `real` arithmetic assumes
    a nonzero remainder."""
    return (
        shards > 1
        and height % WORD == 0
        and (height // WORD) // shards >= 1
        and (height // WORD) % shards != 0
    )


def halo_step_packed(p: jax.Array, rule: Rule, axis: str = AXIS) -> jax.Array:
    """One turn on a local packed strip, halos over `axis`.

    Shift semantics mirror bitlife._shift_up/_shift_down, except the
    cross-word carry at the strip edges comes from the exchanged halo
    words instead of this shard's own wraparound."""
    above_last, below_first = edge_exchange(p, axis)

    # result[y] = orig[y-1]: carry word for word-row r is word-row r-1;
    # for r=0 it is the upper neighbour's last word-row.
    carry_up = jnp.concatenate([above_last, p[:-1]], axis=0)
    up = (p << jnp.uint32(1)) | (carry_up >> jnp.uint32(WORD - 1))

    # result[y] = orig[y+1]: carry word for word-row r is word-row r+1;
    # for the last r it is the lower neighbour's first word-row.
    carry_down = jnp.concatenate([p[1:], below_first], axis=0)
    down = (p >> jnp.uint32(1)) | (carry_down << jnp.uint32(WORD - 1))

    return bitlife.combine_packed(p, up, down, rule)


#: Ghost slab depth (word-rows per side) for the pallas local path —
#: the single-chip kernels' measured sweet spot (ops/pallas_bitlife).
DEEP_WORDS = 4


def _strip_shape_factor(r: int) -> float:
    """Throughput discount of thin tile heights — the dependency-chain
    wall (docs/PERF.md, the 512² study). The production constant 2.6
    sits between the committed r5 fits (forced-r sweeps at
    2048²/8192²/16384², r in 8..64, scripts/kernel_ab.py): the
    authoritative LIFE-ONLY fit is c=2.8 at 2.55% relative rms
    (per-shape 2.1-3.4, BENCH_DETAIL kernel_ab.fit_life_only); the
    joint fit including the gens points reads c=2.1 at 5.7% rms, but
    plane-scaled VMEM pressure distorts the gens r-trend, so the
    production constant follows the life-only fit. Selection is
    insensitive between 2.1 and 2.8 — one delta in the 104-config
    sweep — and at that one config (1024-word shards 8192 wide) the
    choice measured 11% faster (kernel_ab.selection_ab). The r4
    single-shape constant (6) overstated the thin-strip penalty."""
    return r / (r + 2.6)


def search_local_block_mode(strip_words: int, plan_1d, plan_2d,
                            max_h: int | None = None):
    """Best (ghost depth, 'tiled'|'tiled2d') over ppermute slab depths,
    scoring each candidate by ghost overhead x inner tiling efficiency
    x the thin-strip shape factor — the ONE search both the Life and
    the Generations rings use (the plan callables inject the family's
    kernels). `plan_1d(ext_rows) -> (r, inner_halo) | None`;
    `plan_2d(ext_rows) -> (r, inner_halo, tile_width) | None` — both
    must describe the plan the kernel will actually execute. `max_h`
    caps the slab depth (the balanced split needs every ghost to come
    whole from ONE neighbour, so h <= the shortest shard). Returns
    None when nothing fits."""
    from gol_tpu.ops.pallas_bitlife import TILE2D_GHOST_LANES

    best = None
    for h in (4, 8, 16, 32, 64):
        if h >= strip_words or (max_h is not None and h > max_h):
            break
        e = strip_words + 2 * h
        if e % 8 != 0:
            continue
        outer = strip_words / e
        p1 = plan_1d(e)
        if p1 is not None:
            r, hi = p1
            eff = outer * (r / (r + 2 * hi)) * _strip_shape_factor(r)
            if best is None or eff > best[0]:
                best = (eff, h, "tiled")
        p2 = plan_2d(e)
        if p2 is not None:
            r2, h2, wt = p2
            eff = (outer * (r2 / (r2 + 2 * h2))
                   * (wt / (wt + 2 * TILE2D_GHOST_LANES))
                   * _strip_shape_factor(r2))
            if best is None or eff > best[0]:
                best = (eff, h, "tiled2d")
    return (best[1], best[2]) if best is not None else None


def local_block_mode(strip_words: int, width: int, on_tpu: bool,
                     force: bool | None = None,
                     max_h: int | None = None) -> tuple:
    """(ghost depth h, local stepping mode) for a shard's deep blocks.

    'whole': the ghost-extended block fits VMEM — the single-chip
    VMEM-resident pallas kernel steps it. 'tiled'/'tiled2d': too big
    for VMEM but tile-aligned — the strip-tiled (or, for wide shards,
    the 2-D tiled) pallas kernel steps it (both are exact toroidal
    steppers, and the ext block's wrap garbage is the same garbage the
    ghost analysis already wrote off); the ghost depth is a ppermute
    slab, not an 8-row block fetch, so `search_local_block_mode` picks
    the best (h, kernel) pair. 'xla': the fori_loop fallback with
    one-word ghosts (off-TPU unless `force`, or misaligned shapes)."""
    from gol_tpu.ops import pallas_bitlife

    if force is False:
        return 1, "xla"
    if width % 128 == 0 and (on_tpu or force):
        ext = strip_words + 2 * DEEP_WORDS
        if (ext % 8 == 0
                and (max_h is None or DEEP_WORDS <= max_h)
                and ext * width * 4 * 10 <= pallas_bitlife.VMEM_BUDGET_BYTES):
            return DEEP_WORDS, "whole"

        def plan_1d(e):
            if not pallas_bitlife.fits_pallas_packed_tiled(e * WORD, width):
                return None
            # The tiled kernel's own planner supplies (inner strip,
            # inner halo) — the score models the exact plan
            # step_n_packed_pallas_tiled_raw will execute.
            return pallas_bitlife._tile_plan(e, width, None, None)

        def plan_2d(e):
            if not pallas_bitlife.fits_pallas_packed_tiled2d(e * WORD, width):
                return None
            r2 = pallas_bitlife._tile2d_rows(e)
            h2 = pallas_bitlife._halo_words(
                r2,
                pallas_bitlife.TILE2D_WIDTH
                + 2 * pallas_bitlife.TILE2D_GHOST_LANES,
            )
            return r2, h2, pallas_bitlife.TILE2D_WIDTH

        found = search_local_block_mode(strip_words, plan_1d, plan_2d, max_h)
        if found is not None:
            return found
    return 1, "xla"


def packed_ring_halo_cost(n: int, strip_words: int, on_tpu: bool,
                          force_local_pallas: "bool | None",
                          max_h: "int | None" = None):
    """Host-side ring-traffic accounting for a packed ring — the
    `Stepper.halo_cost` hook (gol_tpu.obs). Pure arithmetic over the
    SAME (ghost depth, mode) block plan step_n compiles via
    `local_block_mode`, so the priced collectives are the dispatched
    ones; bytes are uint32 word-rows (4W per word-row per direction),
    both directions, summed over all shards. `per_turn=True` prices
    the scanned diff paths, which ppermute one edge word-row per
    turn. Never touches the device and never runs under trace."""

    def halo_cost(world, k, per_turn: bool = False) -> dict:
        k = max(int(k), 0)
        w = int(world.shape[-1])
        if per_turn:
            sends, word_rows = 2 * k, 2 * k
        else:
            h, mode = local_block_mode(
                strip_words, w, on_tpu, force_local_pallas, max_h=max_h
            )
            big, k2 = divmod(k, WORD * h)
            if mode == "xla":
                mid, rem = divmod(k2, WORD)
                part = 0
            else:
                # Pallas local blocks absorb the whole tail as ONE
                # partial block at the full ghost depth.
                mid, rem = 0, 0
                part = 1 if k2 else 0
            sends = 2 * (big + part + mid + rem)
            word_rows = 2 * ((big + part) * h + mid + rem)
        return {"exchanges": sends * n, "bytes": word_rows * w * 4 * n}

    return halo_cost


def packed_sharded_stepper(rule: Rule, devices: list, height: int,
                           force_local_pallas: bool | None = None):
    """Stepper whose world lives packed AND row-sharded: (H/32, W) uint32
    sharded into contiguous word-row strips across `devices`.

    `force_local_pallas` overrides the TPU-only gate on the pallas
    local-block path (True runs it in interpreter mode on CPU meshes —
    tests use this to exercise the pallas-inside-shard_map composition
    without chips; False pins the XLA path)."""
    from gol_tpu.parallel.stepper import Stepper

    n = len(devices)
    if not packable_sharded(height, n):
        raise ValueError(
            f"height {height} not packable into {n} whole-word strips"
        )
    table = partition.table_for("packed_ring")
    mesh = partition.ring_mesh(devices)
    spec = table.resolve("world", ndim=2)
    sharding = partition.named_sharding(mesh, spec)
    on_tpu = devices[0].platform == "tpu"
    strip_words = (height // n) // WORD

    def deep_block(block, h: int, mode: str, turns: int):
        """One h-word exchange, `turns` (<= 32*h) exact local turns (see
        module docstring and `local_block_mode`)."""
        from gol_tpu.ops import pallas_bitlife

        assert 1 <= turns <= WORD * h
        above_last, below_first = edge_exchange(block, AXIS, depth=h)
        ext = jnp.concatenate([above_last, block, below_first], axis=0)
        if mode == "whole":
            # Pallas kernel bodies are traced under the shard_map
            # context and pltpu.roll does not propagate the varying-axis
            # tag, so the in-kernel loop carry would fail vma checking —
            # pallas-mode programs run their shard_map with
            # check_vma=False instead (see step_n), and correctness is
            # pinned by the bit-exact cross-backend tests.
            ext = pallas_bitlife.step_n_packed_pallas_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        elif mode == "tiled":
            ext = pallas_bitlife.step_n_packed_pallas_tiled_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        elif mode == "tiled2d":
            ext = pallas_bitlife.step_n_packed_pallas_tiled2d_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        else:
            ext = lax.fori_loop(
                0, turns, lambda _, q: bitlife.step_packed(q, rule), ext
            )
        return ext[h:-h]

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n(p, k):
        # divmod would floor a negative k into 31 remainder turns;
        # preserve the fori_loop contract that k <= 0 is a no-op.
        h, mode = local_block_mode(
            strip_words, p.shape[1], on_tpu, force_local_pallas
        )
        big, k2 = divmod(max(k, 0), WORD * h)
        if mode == "xla":
            # One-word ghosts: 32-turn blocks, per-turn tail.
            mid, rem = divmod(k2, WORD)
        else:
            # Pallas local blocks accept any turn count, so the whole
            # tail runs as ONE partial block at the fast-path rate (its
            # ghost depth is already aligned; a shallower one might not
            # be) instead of per-turn XLA steps.
            mid, rem = 0, 0

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=spec,
            out_specs=(spec, partition.REPLICATED),
            # vma checking must be off when a pallas local path is in
            # the program (see deep_block); every other variant keeps it.
            check_vma=mode == "xla",
        )
        def _many(block):
            block = lax.fori_loop(
                0, big, lambda _, q: deep_block(q, h, mode, WORD * h), block
            )
            if mode != "xla" and k2:
                block = deep_block(block, h, mode, k2)
            block = lax.fori_loop(
                0, mid, lambda _, q: deep_block(q, 1, "xla", WORD), block
            )
            block = lax.fori_loop(
                0, rem, lambda _, q: halo_step_packed(q, rule), block
            )
            count = lax.psum(bitlife.count_packed(block), AXIS)
            return block, count

        return _many(p)

    @jax.jit
    def step(p):
        return step_n(p, 1)[0]

    from gol_tpu.parallel.multihost import spmd_fetch, spmd_put

    @jax.jit
    def step_with_diff(p):
        new, count = step_n(p, 1)
        mask = bitlife.unpack(p ^ new, height) != 0
        return new, mask, count

    @jax.jit
    def count(p):
        return bitlife.count_packed(p)

    def put(w):
        # Pack on the host so every process can slice its own shard of
        # the packed words (device-side packing would need the dense
        # board as a global array first).
        return spmd_put(sharding, bitlife.pack_np(w))

    def fetch(arr):
        if getattr(arr, "dtype", None) == jnp.uint32:
            return bitlife.unpack_np(spmd_fetch(arr), height)
        return spmd_fetch(arr)

    from gol_tpu.parallel.stepper import scan_diffs, sparse_scan_diffs

    # Per-turn ring halos inside one scanned program; the diff stack
    # stays packed (k, H/32, W) and word-row-sharded until the engine's
    # single gather. (Per-turn halo exchange, not deep blocks: the diff
    # path needs every intermediate board anyway.)
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=spec, out_specs=spec
    )
    def _one_turn(block):
        return halo_step_packed(block, rule)

    _snd = scan_diffs(_one_turn, lambda old, new: old ^ new, count)
    # Sparse rows over the same per-turn scan (VERDICT r4 Missing #2):
    # the encode runs under jit over the sharded diff (XLA gathers),
    # and the rows are pinned replicated so a multiprocess coordinator
    # can materialize them with a plain np.asarray.
    _snd_sparse = sparse_scan_diffs(
        _one_turn, lambda old, new: old ^ new, count,
        post=replicate_rows(mesh),
    )
    # Variable-length compact chunks (r6): same per-turn scan, shared
    # value buffer, headers + values replicated.
    from gol_tpu.parallel.stepper import compact_scan_diffs

    _snd_compact = compact_scan_diffs(
        _one_turn, lambda old, new: old ^ new, count,
        post=replicate_compact(mesh),
    )

    _sync = cpu_serializing_sync(devices)

    return Stepper(
        name=f"packed-halo-ring-{n}",
        shards=n,
        put=put,
        fetch=fetch,
        step=lambda p: _sync(step(p)),
        step_n=lambda p, k: _sync(step_n(p, int(k))),
        step_with_diff=lambda p: _sync(step_with_diff(p)),
        alive_count_async=lambda p: _sync(count(p)),
        step_n_with_diffs=lambda p, k: _sync(_snd(p, int(k))),
        fetch_diffs=spmd_fetch,
        packed_diffs=True,
        step_n_with_diffs_sparse=lambda p, k, cap: _sync(
            _snd_sparse(p, int(k), int(cap))
        ),
        step_n_with_diffs_compact=lambda p, k, cap: _sync(
            _snd_compact(p, int(k), int(cap))
        ),
        halo_cost=packed_ring_halo_cost(
            n, strip_words, on_tpu, force_local_pallas
        ),
    )


def halo_step_packed_balanced(p: jax.Array, rule: Rule, real,
                              axis: str = AXIS) -> jax.Array:
    """One turn on a balanced-split packed strip: the shard's physical
    block is Sw word-rows, of which the first `real` (a traced scalar
    from lax.axis_index) are owned; padding word-rows below stay zero.

    The deviations from halo_step_packed, all at word granularity:
    - the word-row sent down the ring is the last REAL one (index
      real-1, not Sw-1);
    - the cross-word carry for word-row real-1's down-shift is the
      below neighbour's first word-row, spliced in at its dynamic
      position;
    - padding word-rows are forced zero after the combine (their
      neighbour counts are garbage)."""
    Sw = p.shape[0]
    down, up = ring_perms(lax.axis_size(axis))
    send_down = lax.dynamic_slice(
        p, (real - 1, jnp.int32(0)), (1, p.shape[1])
    )
    above_last = lax.ppermute(send_down, axis, down)
    below_first = lax.ppermute(p[:1], axis, up)

    carry_up = jnp.concatenate([above_last, p[:-1]], axis=0)
    up_b = (p << jnp.uint32(1)) | (carry_up >> jnp.uint32(WORD - 1))

    carry_down = jnp.concatenate([p[1:], below_first], axis=0)
    carry_down = lax.dynamic_update_slice(
        carry_down, below_first, (real - 1, jnp.int32(0))
    )
    down_b = (p >> jnp.uint32(1)) | (carry_down << jnp.uint32(WORD - 1))

    new = bitlife.combine_packed(p, up_b, down_b, rule)
    wid = lax.broadcasted_iota(jnp.int32, (Sw, 1), 0)
    return jnp.where(wid < real, new, jnp.zeros_like(new))


def balanced_words(height: int, n: int) -> tuple:
    """(Sw, real_list) of the word-granular balanced split: every
    shard's physical strip is Sw = ceil(total_words/n) word-rows;
    shard i really owns Sw words iff i < total_words mod n, else
    Sw-1 — the halo._sharded_stepper_uneven layout at word
    granularity."""
    total_words = height // WORD
    Sw = -(-total_words // n)
    rem = total_words % n
    if rem == 0:  # divisible: every shard owns exactly Sw (even split)
        return Sw, [Sw] * n
    return Sw, [Sw if i < rem else Sw - 1 for i in range(n)]


def replicate_rows(mesh):
    """`post` hook for sparse_scan_diffs on ring steppers: pin the
    per-turn sparse rows fully replicated over `mesh`, so np.asarray
    materializes them on any process without a host collective."""
    def post(new, rows, count):
        rows = jax.lax.with_sharding_constraint(
            rows, partition.named_sharding(mesh, partition.REPLICATED)
        )
        return new, rows, count

    return post


def replicate_compact(mesh):
    """`post` hook for compact_scan_diffs on ring steppers: pin the
    headers AND the shared value buffer fully replicated over `mesh`
    (same rationale as replicate_rows — multiprocess coordinators
    materialize both with plain np.asarray)."""
    rep = partition.named_sharding(mesh, partition.REPLICATED)

    def post(new, headers, values, count):
        headers = jax.lax.with_sharding_constraint(headers, rep)
        values = jax.lax.with_sharding_constraint(values, rep)
        return new, headers, values, count

    return post


def strip_padding(arr, Sw: int, real_list, axis: int = -2):
    """Cut the balanced split's padding out of a padded word-row axis:
    (..., n*Sw, ...) -> (..., total_words, ...), keeping each shard's
    first real_list[i] rows. The ONE definition of the padded->canonical
    layout map — device-side (_strip under jit) and host-side
    (fetch/fetch_diffs) callers in both families share it, so the
    layout cannot drift between the six call sites."""
    xp = jnp if isinstance(arr, jax.Array) else np
    index = [slice(None)] * arr.ndim
    parts = []
    for i, real in enumerate(real_list):
        index[axis] = slice(i * Sw, i * Sw + real)
        parts.append(arr[tuple(index)])
    return xp.concatenate(parts, axis=axis)


def packed_sharded_stepper_uneven(rule: Rule, devices: list, height: int,
                                  force_local_pallas: bool | None = None):
    """The balanced-split variant of `packed_sharded_stepper`: device
    state is (n*Sw, W) packed word-rows with each shard's real rows at
    the top of its strip (`balanced_words`), padding rows kept zero —
    so NON-DIVISOR shard counts keep the SWAR ring, the deep halos AND
    the pallas local blocks instead of falling back to the per-turn
    dense ring (VERDICT r4 Missing #1 / Weak #3; ref worker contract:
    any count 1..16 at full machinery, ref: gol/distributor.go:124-155).

    Deep blocks work exactly as in the even ring — a ghost slab is h
    word-rows = 32h complete rows buying 32h exact local turns — with
    two dynamic touches: the upward-sent slab starts at real-h, and
    the below-ghost is spliced in directly after the last real row, so
    the light-cone argument sees contiguous real rows. h is capped at
    the shortest shard (every ghost comes whole from ONE neighbour)."""
    from gol_tpu.parallel.stepper import Stepper, scan_diffs

    n = len(devices)
    if not packable_sharded_uneven(height, n):
        raise ValueError(
            f"height {height} not balance-packable over {n} shards"
        )
    total_words = height // WORD
    Sw, real_list = balanced_words(height, n)
    rem_words = total_words % n
    floor_words = total_words // n
    offsets = np.concatenate([[0], np.cumsum(real_list)])
    table = partition.table_for("packed_ring")
    mesh = partition.ring_mesh(devices)
    spec = table.resolve("world", ndim=2)
    sharding = partition.named_sharding(mesh, spec)
    on_tpu = devices[0].platform == "tpu"

    def _real():
        idx = lax.axis_index(AXIS)
        return jnp.where(idx < rem_words, jnp.int32(Sw), jnp.int32(Sw - 1))

    def deep_block(block, h: int, mode: str, turns: int, real):
        """One h-word exchange, `turns` (<= 32*h) exact local turns.
        The toroidal wrap garbage and the padding tail both sit >= 32h
        bit-rows from any real row, so the one-row-per-turn validity
        shrink never reaches them (same argument as the even ring,
        plus the padding tail behind the spliced below-ghost)."""
        from gol_tpu.ops import pallas_bitlife

        assert 1 <= turns <= WORD * h
        down, up = ring_perms(n)
        send_down = lax.dynamic_slice(
            block, (real - h, jnp.int32(0)), (h, block.shape[1])
        )
        above = lax.ppermute(send_down, AXIS, down)
        below = lax.ppermute(block[:h], AXIS, up)
        ext = jnp.concatenate(
            [above, block, jnp.zeros_like(block[:h])], axis=0
        )
        ext = lax.dynamic_update_slice(
            ext, below, (h + real, jnp.int32(0))
        )
        if mode == "whole":
            ext = pallas_bitlife.step_n_packed_pallas_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        elif mode == "tiled":
            ext = pallas_bitlife.step_n_packed_pallas_tiled_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        elif mode == "tiled2d":
            ext = pallas_bitlife.step_n_packed_pallas_tiled2d_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        else:
            ext = lax.fori_loop(
                0, turns, lambda _, q: bitlife.step_packed(q, rule), ext
            )
        out = ext[h : h + Sw]
        wid = lax.broadcasted_iota(jnp.int32, (Sw, 1), 0)
        return jnp.where(wid < real, out, jnp.zeros_like(out))

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n(p, k):
        h, mode = local_block_mode(
            Sw, p.shape[1], on_tpu, force_local_pallas, max_h=floor_words
        )
        big, k2 = divmod(max(k, 0), WORD * h)
        if mode == "xla":
            mid, rem_t = divmod(k2, WORD)
        else:
            mid, rem_t = 0, 0

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=spec,
            out_specs=(spec, partition.REPLICATED),
            # vma checking off when a pallas local path is in the
            # program (pltpu.roll drops the varying-axis tag — see
            # packed_sharded_stepper).
            check_vma=mode == "xla",
        )
        def _many(block):
            real = _real()
            block = lax.fori_loop(
                0, big,
                lambda _, q: deep_block(q, h, mode, WORD * h, real), block
            )
            if mode != "xla" and k2:
                block = deep_block(block, h, mode, k2, real)
            block = lax.fori_loop(
                0, mid,
                lambda _, q: deep_block(q, 1, "xla", WORD, real), block
            )
            block = lax.fori_loop(
                0, rem_t,
                lambda _, q: halo_step_packed_balanced(q, rule, real), block
            )
            # Padding words are zero, so the plain popcount + psum is
            # already the exact global count.
            count = lax.psum(bitlife.count_packed(block), AXIS)
            return block, count

        return _many(p)

    @jax.jit
    def step(p):
        return step_n(p, 1)[0]

    def _strip(d):
        """(..., n*Sw, W) padded word-rows -> (..., total_words, W)."""
        return strip_padding(d, Sw, real_list)

    @jax.jit
    def step_with_diff(p):
        new, count = step_n(p, 1)
        mask = bitlife.unpack(_strip(p ^ new), height) != 0
        return new, mask, count

    @jax.jit
    def count(p):
        return bitlife.count_packed(p)

    from gol_tpu.parallel.multihost import spmd_fetch, spmd_put

    def put(w):
        words = bitlife.pack_np(w)
        padded = np.zeros((n * Sw, words.shape[1]), np.uint32)
        for i in range(n):
            padded[i * Sw : i * Sw + real_list[i]] = (
                words[offsets[i] : offsets[i + 1]]
            )
        return spmd_put(sharding, padded)

    def fetch(arr):
        if getattr(arr, "dtype", None) == jnp.uint32:
            words = strip_padding(spmd_fetch(arr), Sw, real_list)
            return bitlife.unpack_np(words, height)
        return spmd_fetch(arr)

    def fetch_diffs(d):
        # (k, n*Sw, W) padded diff stack -> (k, total_words, W): padding
        # rows are zero on both sides of every turn but must be cut out
        # so word-row indices map to global rows.
        return strip_padding(spmd_fetch(d), Sw, real_list)

    # Per-turn ring halos for the diff scan, exactly as the even ring.
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=spec, out_specs=spec
    )
    def _one_turn(block):
        return halo_step_packed_balanced(block, rule, _real())

    _snd = scan_diffs(_one_turn, lambda old, new: old ^ new, count)
    # Sparse/compact rows over the canonical layout: the diff is
    # stripped of padding ON DEVICE, so the encode covers exactly
    # (H/32)*W words — the engine's decoders need no balanced-split
    # awareness.
    from gol_tpu.parallel.stepper import compact_scan_diffs, sparse_scan_diffs

    _snd_sparse = sparse_scan_diffs(
        _one_turn, lambda old, new: _strip(old ^ new), count,
        post=replicate_rows(mesh),
    )
    _snd_compact = compact_scan_diffs(
        _one_turn, lambda old, new: _strip(old ^ new), count,
        post=replicate_compact(mesh),
    )

    _sync = cpu_serializing_sync(devices)

    return Stepper(
        name=f"packed-halo-ring-uneven-{n}",
        shards=n,
        put=put,
        fetch=fetch,
        step=lambda p: _sync(step(p)),
        step_n=lambda p, k: _sync(step_n(p, int(k))),
        step_with_diff=lambda p: _sync(step_with_diff(p)),
        alive_count_async=lambda p: _sync(count(p)),
        step_n_with_diffs=lambda p, k: _sync(_snd(p, int(k))),
        fetch_diffs=fetch_diffs,
        packed_diffs=True,
        step_n_with_diffs_sparse=lambda p, k, cap: _sync(
            _snd_sparse(p, int(k), int(cap))
        ),
        step_n_with_diffs_compact=lambda p, k, cap: _sync(
            _snd_compact(p, int(k), int(cap))
        ),
        halo_cost=packed_ring_halo_cost(
            n, Sw, on_tpu, force_local_pallas, max_h=floor_words
        ),
    )
