"""Stepper — the engine's pluggable execution backend.

The reference picks between a serial sweep and a goroutine row-farm on
`Threads` (ref: gol/distributor.go:93-115 vs :116-173). Here the choice
is between a single-device kernel and a row-strip-sharded kernel over a
device mesh; `Params.threads` is the *requested shard count*, and —
exactly like the reference, where any thread count 1..16 yields
identical boards (ref: gol_test.go:16-31) — the actual shard count is an
internal detail that never changes results. The factory clamps the
request to what the hardware and the grid height allow.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional

import jax
import numpy as np

from gol_tpu.models.rules import LIFE, GenRule, Rule, get_rule
from gol_tpu.ops import life
from gol_tpu.params import BACKENDS


@dataclasses.dataclass(frozen=True)
class EntryInfo:
    """One row of the Stepper capability table (`ENTRY_TABLE`): what a
    Stepper field IS, so the consumers that used to hand-maintain
    parallel lists (the obs instrumentation wrapper, the SPMD dispatch
    mirror, the engine's capability probes) derive their behaviour from
    ONE declaration. multihost.py drifted once exactly because its
    per-opcode shims were written by hand (PR 4's redo-token bug) —
    the table is the fix.

    Fields:
    - `kind`: "core" (every backend offers it), "diff" (optional diff
      scans), "fetch" (host-materialization hooks), "meta" (host-side
      metadata, never dispatched).
    - `wrap`: instrument_stepper's wrapper shape — "put" (timed +
      cost probe), "timed", "one_turn" / "step_n" / "diffy" (halo
      charging variants), None = not instrumented.
    - `opcode`: the SPMD mirror's broadcast opcode. STABLE numbers —
      they are the coordinator/worker wire protocol (multihost.py
      reads them from here; fetch disambiguates world/mask with its
      own pair of opcodes, and STOP is the control channel's own).
    - `args`: how many int64 arguments ride the opcode broadcast
      (chunk size k, sparse/compact cap).
    - `token`: the sparse-redo token discipline role — "reset" (a
      fused dispatch consumes any outstanding sparse record), "dense"
      (must continue from the sparse output), "sparse" (records its
      (input, output) pair), "redo" (must re-step the sparse input).
    - `replay`: how a worker process co-executes the opcode
      (spmd_worker_loop); None = never broadcast or fetch-family.
    """

    name: str
    kind: str
    wrap: Optional[str] = None
    opcode: Optional[int] = None
    args: int = 0
    token: Optional[str] = None
    replay: Optional[str] = None


#: The capability table — one row per Stepper field, in field order.
#: Every consumer that enumerates entries (instrument_stepper, the
#: SPMD mirror and worker loop, engine/session capability probes via
#: `Stepper.offers`) reads THIS, never a hand-copied list.
ENTRY_TABLE: tuple = (
    EntryInfo("put", "core", wrap="put", opcode=0, token="reset",
              replay="put"),
    EntryInfo("fetch", "core", wrap="timed", replay="fetch"),
    EntryInfo("step", "core", wrap="one_turn", opcode=1, token="reset",
              replay="step"),
    EntryInfo("step_n", "core", wrap="step_n", opcode=2, args=1,
              token="reset", replay="step_n"),
    EntryInfo("step_with_diff", "core", wrap="one_turn", opcode=3,
              replay="diff"),
    EntryInfo("alive_count_async", "core", opcode=4, replay="count"),
    EntryInfo("alive_mask", "meta"),
    EntryInfo("step_n_with_diffs", "diff", wrap="diffy", opcode=8,
              args=1, token="dense", replay="dense"),
    EntryInfo("fetch_diffs", "fetch", opcode=9, replay="fetch_diffs"),
    EntryInfo("packed_diffs", "meta"),
    EntryInfo("step_n_with_diffs_sparse", "diff", wrap="diffy",
              opcode=10, args=2, token="sparse", replay="sparse"),
    EntryInfo("step_n_with_diffs_redo", "diff", wrap="diffy",
              opcode=11, args=1, token="redo", replay="redo"),
    EntryInfo("step_n_with_diffs_compact", "diff", wrap="diffy",
              opcode=12, args=2, token="sparse", replay="compact"),
    EntryInfo("fetch_compact_values", "fetch"),
    EntryInfo("halo_cost", "meta"),
    EntryInfo("tiled", "meta"),
)


def entries(kind: Optional[str] = None) -> tuple:
    """Capability-table rows, optionally filtered by `kind`."""
    if kind is None:
        return ENTRY_TABLE
    return tuple(e for e in ENTRY_TABLE if e.kind == kind)


def entry_info(name: str) -> EntryInfo:
    for e in ENTRY_TABLE:
        if e.name == name:
            return e
    raise KeyError(f"no Stepper entry named {name!r}")


@dataclasses.dataclass
class Stepper:
    """Uniform interface over execution strategies.

    All worlds are {0,255} uint8 of shape (H, W); `put` moves a host
    array onto device(s) with the stepper's sharding, `fetch` brings one
    back. Step functions are jitted and reused across turns.
    """

    name: str
    shards: int
    put: Callable
    fetch: Callable
    #: world -> world (one turn; plain API convenience)
    step: Callable
    #: (world, k) -> (world, count_scalar): k turns + alive count, fused
    #: into ONE device program. Exactly one program runs at a time and
    #: only the engine thread ever dispatches or realises device values —
    #: a second thread touching the device wedges the collective
    #: rendezvous on hosts with few cores (see engine.distributor).
    step_n: Callable
    #: world -> (world, flipped_mask, count_scalar), one fused program
    step_with_diff: Callable
    #: world -> count device scalar (engine thread only)
    alive_count_async: Callable
    #: host-levels -> bool mask of ALIVE cells for event payloads.
    #: None = two-state convention (nonzero is alive); multi-state
    #: backends (Generations) override it so dying cells — nonzero
    #: gray levels — are not reported as alive.
    alive_mask: Optional[Callable] = None
    #: (world, k) -> (world, diffs, count_scalar): k turns with the
    #: per-turn flip masks accumulated ON DEVICE and returned as one
    #: stacked array — uint32 (k, H/32, W) packed word-rows (bitlife
    #: layout) for packed backends, bool (k, H, W) for dense ones. The
    #: engine ships the whole stack in ONE host transfer and expands it
    #: to per-turn CellFlipped batches with NumPy, replacing k dispatch
    #: + fetch round trips with one (VERDICT r3 Weak #1: the per-turn
    #: path paid the ~100 ms link latency every single turn).
    step_n_with_diffs: Optional[Callable] = None
    #: device diff stack -> host ndarray in canonical layout (leading
    #: axis = turn). None = plain np.asarray; sharded backends override
    #: to gather (and the uneven split to strip its padding rows).
    fetch_diffs: Optional[Callable] = None
    #: True when `step_n_with_diffs` rows are packed uint32 word-rows
    #: (H*W/8 bytes per turn) rather than dense bool masks (H*W) — the
    #: engine sizes its diff-chunk budget from this, so packed big
    #: boards get the full DIFF_STACK_BUDGET instead of chunks 8x
    #: smaller than the stack actually is (ADVICE r4).
    packed_diffs: bool = False
    #: (world, k, cap) -> (world, sparse_stack, count): the diff scan
    #: with each turn's flip mask SPARSE-encoded on device. One int32
    #: row per turn, laid out [changed_word_count (1), changed-word
    #: BITMAP (total_words/32 words), changed-word values (cap)] —
    #: exactly what sparse_scan_diffs emits and sparse_decode_rows
    #: reads; implement new backends through those helpers so the
    #: layout cannot drift. On a slow host link this is the engine's
    #: steady-state watched path: a changed word costs 4 bytes plus its
    #: bitmap bit instead of the mask's 4 bytes per word, changed or
    #: not. A count above `cap` means that turn's value list is
    #: truncated — the engine detects it and redoes the chunk with the
    #: dense stack (never trusts truncated data). Offered by every
    #: packed backend: single-device, the ring steppers (even and
    #: balanced-split, both families — rows cover the CANONICAL word
    #: layout, padding stripped on device, and are replicated so any
    #: process can materialize them without a collective), and the
    #: SPMD mirror (r5; VERDICT r4 Missing #2).
    step_n_with_diffs_sparse: Optional[Callable] = None
    #: (world, k) -> (world, diffs, count): the EXPLICIT sparse-overflow
    #: redo — same signature and result as `step_n_with_diffs`, but the
    #: contract is different: `world` must be the exact input of the
    #: immediately preceding sparse/compact call whose rows came back
    #: truncated. The engine prefers this entry for redos so mirrored
    #: steppers can broadcast a dedicated redo opcode instead of
    #: guessing from object identity (a guess that would silently
    #: diverge the ring if the dispatch pattern ever changed — ADVICE
    #: r5 #2). None = redo rides plain `step_n_with_diffs`
    #: (single-process steppers don't care).
    step_n_with_diffs_redo: Optional[Callable] = None
    #: (world, k, total_cap) -> (world, headers, values, count): the
    #: VARIABLE-LENGTH diff scan (r6). Where the sparse rows above
    #: reserve `cap` value slots for EVERY turn (a fixed-width row, so
    #: a quiet turn still ships the whole slab), this entry prefix-sums
    #: the per-turn changed counts on device and scatters each turn's
    #: changed words into ONE shared (total_cap,) value buffer:
    #:   headers: (k, 1 + total_words/32) int32 — [count, bitmap] per
    #:            turn, no value slots;
    #:   values:  (total_cap,) int32 — every turn's changed words,
    #:            back to back, ascending word index within a turn.
    #: The host fetches the headers (4k + k·nb·4 bytes), sums the
    #: counts, and fetches only the USED value prefix (~4·Σmₜ bytes) —
    #: the link pays for actual activity instead of the cap. Overflow
    #: (Σmₜ > total_cap) is detected from the summed host-side counts;
    #: the chunk is then redone densely via `step_n_with_diffs_redo`,
    #: exactly like a truncated sparse row. Built by
    #: `compact_scan_diffs`, decoded by `compact_decode_rows`, offered
    #: by every packed backend (rows cover the CANONICAL word layout,
    #: balanced splits strip padding on device; ring outputs are
    #: replicated).
    step_n_with_diffs_compact: Optional[Callable] = None
    #: (values_device, total) -> host uint32 array of >= total words:
    #: how the engine fetches the used value prefix of a compact chunk.
    #: None = `compact_value_prefix` (pow2-bucketed device slice —
    #: fine whenever the buffer is addressable from this process);
    #: the SPMD mirror overrides it to materialize the replicated
    #: buffer whole (a coordinator-only device slice on a
    #: cross-process array would not be addressable).
    fetch_compact_values: Optional[Callable] = None
    #: (world, k, per_turn) -> {"exchanges": int, "bytes": int}: HOST-
    #: SIDE accounting of the ring traffic one k-turn dispatch of this
    #: stepper generates — pure arithmetic over the same block plan the
    #: jitted step_n compiles (deep blocks vs per-turn halos), never a
    #: device call. `per_turn=True` prices the scanned diff paths,
    #: which exchange every turn. None = no collectives (single-device
    #: backends). Feeds gol_tpu_halo_* (gol_tpu.obs); the jitted
    #: programs themselves stay untouched — the obs-in-jit linter check
    #: enforces that metrics never enter a trace.
    halo_cost: Optional[Callable] = None
    #: The activity-driven tiled backend's host-side implementation
    #: (parallel/tiled.TiledStepper) — None on every dense backend.
    #: Engines read it to stand their whole-board cycle machinery down
    #: (per-tile riding subsumes it, and the tiled world handle is
    #: mutated in place, so an anchor reference would alias the moving
    #: state); tests and the bench reach the activity plane (pool
    #: census, ride cache) through it. Survives instrument_stepper /
    #: checked_stepper (both are dataclasses.replace).
    tiled: Optional[object] = None

    def alive_count(self, world) -> int:
        return int(self.alive_count_async(world))

    def offers(self, entry: str) -> bool:
        """True when this backend provides capability-table entry
        `entry` — the ONE probe the engine, sessions, tiling and the
        SPMD mirror use (never `hasattr` or `is not None` on fields
        directly: the table validates the name, so a typo'd probe
        raises instead of silently reading False forever)."""
        entry_info(entry)  # KeyError on names the table doesn't know
        value = getattr(self, entry)
        return value is not None and value is not False

    def capabilities(self) -> tuple:
        """Names of every table entry this backend offers (for the
        bool-valued `packed_diffs` flag, offered means True)."""
        return tuple(e.name for e in ENTRY_TABLE
                     if getattr(self, e.name) not in (None, False))


def _diff_scan(step_fn, diff_fn, count_fn, state, k):
    """The un-jitted k-turn diff scan both `scan_diffs` (single board)
    and the vmapped session-bucket builder trace — one body so the two
    paths cannot drift."""
    from jax import lax as _lax

    def body(q, _):
        new = step_fn(q)
        return new, diff_fn(q, new)

    new, diffs = _lax.scan(body, state, None, length=max(int(k), 0))
    return new, diffs, count_fn(new)


def scan_diffs(step_fn, diff_fn, count_fn, post=None):
    """Build a `step_n_with_diffs` by scanning a single-turn step: the
    carry is the world, the per-turn output is `diff_fn(old, new)`, and
    the alive count is computed once on the final state — all one device
    program. `post` (optional) wraps the scanned (state, diffs, count)
    triple, e.g. to psum a sharded count."""

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n_with_diffs(state, k):
        out = _diff_scan(step_fn, diff_fn, count_fn, state, k)
        return post(*out) if post is not None else out

    return step_n_with_diffs


def sparse_bitmap_words(total_words: int) -> int:
    """int32 words in the changed-word bitmap for a diff space of
    `total_words` packed words — the one layout constant the encoder,
    the engine decoder, and the bench share."""
    return -(-total_words // 32)


def sparse_decode_rows(host_rows, total_words: int):
    """Decode sparse diff rows (see Stepper.step_n_with_diffs_sparse)
    into flat (total_words,) uint32 word arrays — the single host-side
    decoder the engine and the bench share. `host_rows` is the fetched
    (k, 1 + bitmap + cap) stack viewed as uint32. Yields one array per
    turn; raises ValueError on a truncated row (count above the cap the
    row width implies) so callers can fall back to dense masks."""
    import numpy as _np

    nb = sparse_bitmap_words(total_words)
    cap = host_rows.shape[1] - 1 - nb
    shifts = _np.arange(32, dtype=_np.uint32)
    for t in range(host_rows.shape[0]):
        m = int(host_rows[t, 0])
        if m > cap:
            raise ValueError(f"sparse row truncated: {m} > cap {cap}")
        words = _np.zeros(nb * 32, _np.uint32)
        if m:
            bits = (host_rows[t, 1 : 1 + nb, None] >> shifts) & 1
            words[_np.flatnonzero(bits)] = host_rows[t, 1 + nb : 1 + nb + m]
        yield words[:total_words]


def sparse_scan_diffs(step_fn, diff_fn, count_fn, post=None):
    """Build a `step_n_with_diffs_sparse` (see the Stepper field): the
    scanned per-turn output row is

        [changed_count (1), changed-word BITMAP (total/32), values (cap)]

    as one int32 vector. The bitmap (1 bit per packed word) carries the
    positions, so values need no indices — a changed word costs 4 bytes
    plus its bitmap bit, vs 4 bytes/word for the full mask: the row
    beats the mask whenever under ~31/32 of the words changed, and on a
    quiet board it approaches total/8 bytes. Value order is ascending
    word index (jnp.nonzero), matching the host's bitmap scan. A
    changed_count above `cap` marks the value list truncated — the
    consumer must fall back to the dense stack for that chunk.

    Sharded steppers pass `step_fn` = their shard_mapped per-turn halo
    step and a `diff_fn` that emits the CANONICAL flat word layout
    (balanced splits strip padding on device) — the encode then runs
    under plain jit over the sharded diff, XLA inserting the gathers.
    `post` wraps the (state, rows, count) triple, e.g. to pin the rows
    replicated so multiprocess coordinators can np.asarray them."""
    import jax.numpy as jnp
    from jax import lax as _lax

    @functools.partial(jax.jit, static_argnames=("k", "cap"))
    def step_n_with_diffs_sparse(state, k, cap):
        def body(q, _):
            new = step_fn(q)
            d = diff_fn(q, new).reshape(-1)
            nb = sparse_bitmap_words(d.shape[0])
            changed = jnp.pad(d != 0, (0, nb * 32 - d.shape[0]))
            m = jnp.sum(changed, dtype=jnp.int32)
            bits = changed.astype(jnp.uint32).reshape(nb, 32)
            weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
            bitmap = jnp.sum(bits * weights, axis=1, dtype=jnp.uint32)
            idx = jnp.nonzero(d, size=cap, fill_value=0)[0]
            vals = d[idx]
            row = jnp.concatenate(
                [m[None].astype(jnp.uint32), bitmap, vals]
            )
            return new, _lax.bitcast_convert_type(row, jnp.int32)

        new, rows = _lax.scan(body, state, None, length=max(int(k), 0))
        out = (new, rows, count_fn(new))
        return post(*out) if post is not None else out

    return step_n_with_diffs_sparse


def compact_scan_diffs(step_fn, diff_fn, count_fn, post=None):
    """Build a `step_n_with_diffs_compact` (see the Stepper field): one
    scanned program whose per-turn output is only the [count, bitmap]
    header while the changed-word VALUES are stream-compacted — each
    turn's words scattered at offset prefix_sum(counts so far) into one
    shared (total_cap,) buffer carried through the scan. The scatter
    needs no sort and no per-turn cap: within a turn the rank of a
    changed word is cumsum(changed) - 1, so the target index is
    offset + rank where changed, dropped otherwise (out-of-range
    targets — an overflowing chunk — fall into `mode="drop"`; the host
    detects the overflow from the summed counts and never trusts the
    buffer). Value order is ascending word index per turn, matching
    `compact_decode_rows`' bitmap walk.

    Sharded steppers pass their shard_mapped per-turn halo step and a
    canonical-layout diff (as for sparse_scan_diffs); the compaction
    runs under plain jit over the sharded diff, the value buffer stays
    unsharded, and `post` pins headers + values replicated so any
    process can materialize them."""

    @functools.partial(jax.jit, static_argnames=("k", "total_cap"))
    def step_n_with_diffs_compact(state, k, total_cap):
        out = _compact_scan(step_fn, diff_fn, count_fn, state, k, total_cap)
        return post(*out) if post is not None else out

    return step_n_with_diffs_compact


def _compact_scan(step_fn, diff_fn, count_fn, state, k, total_cap):
    """The un-jitted compact-diff scan (layout contract documented on
    `compact_scan_diffs`) shared by the single-board builder above and
    the vmapped session-bucket builder — one body, one layout."""
    import jax.numpy as jnp
    from jax import lax as _lax

    def body(carry, _):
        q, off, buf = carry
        new = step_fn(q)
        d = diff_fn(q, new).reshape(-1)
        nb = sparse_bitmap_words(d.shape[0])
        changed = d != 0
        padded = jnp.pad(changed, (0, nb * 32 - d.shape[0]))
        m = jnp.sum(changed, dtype=jnp.int32)
        bits = padded.astype(jnp.uint32).reshape(nb, 32)
        weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
        bitmap = jnp.sum(bits * weights, axis=1, dtype=jnp.uint32)
        rank = jnp.cumsum(changed, dtype=jnp.int32) - 1
        target = jnp.where(changed, off + rank, jnp.int32(total_cap))
        buf = buf.at[target].set(d, mode="drop")
        header = jnp.concatenate([m[None].astype(jnp.uint32), bitmap])
        return (new, off + m, buf), _lax.bitcast_convert_type(
            header, jnp.int32
        )

    buf0 = jnp.zeros((total_cap,), jnp.uint32)
    (new, _total, buf), headers = _lax.scan(
        body, (state, jnp.int32(0), buf0), None, length=max(int(k), 0)
    )
    return (new, headers, _lax.bitcast_convert_type(buf, jnp.int32),
            count_fn(new))


def compact_decode_rows(headers, values, total_words: int):
    """Decode a compact chunk (see Stepper.step_n_with_diffs_compact)
    into flat (total_words,) uint32 word arrays — the single host-side
    decoder the engine and the bench share. `headers` is the fetched
    (k, 1 + nb) stack viewed as uint32, `values` the (>= Σcounts,)
    uint32 value prefix. Yields one array per turn; raises ValueError
    on any inconsistency — a count disagreeing with its bitmap's
    popcount, or offsets running past the supplied values — so callers
    can reject a truncated/corrupt chunk instead of mis-attributing
    words to turns."""
    import numpy as _np

    nb = sparse_bitmap_words(total_words)
    if headers.ndim != 2 or headers.shape[1] != 1 + nb:
        raise ValueError(
            f"compact header shape {headers.shape} != (k, {1 + nb})"
        )
    shifts = _np.arange(32, dtype=_np.uint32)
    off = 0
    for t in range(headers.shape[0]):
        m = int(headers[t, 0])
        words = _np.zeros(nb * 32, _np.uint32)
        bits = (headers[t, 1 : 1 + nb, None] >> shifts) & 1
        idx = _np.flatnonzero(bits)
        if idx.size != m:
            raise ValueError(
                f"compact turn {t}: bitmap pops {idx.size} words, "
                f"count says {m}"
            )
        if off + m > len(values):
            raise ValueError(
                f"compact chunk truncated: turn {t} needs value words "
                f"{off}..{off + m}, have {len(values)}"
            )
        if m:
            words[idx] = values[off : off + m]
        off += m
        yield words[:total_words]


def compact_value_bucket(total: int) -> int:
    """Fetched-prefix length for `total` used value words: rounded up
    to 1/8th-of-a-power-of-two granularity (floor 1024), so the
    op-by-op slice dispatched per chunk compiles a BOUNDED set of
    distinct shapes over a run (<=8 per octave) while wasting under
    25% of the value bytes worst-case (12.5% at the top of each
    octave) — a plain pow2 bucket measured a 2x hit exactly when Σm
    sat just past a power of two (the settled 512² fixture lands
    there)."""
    if total <= 1024:
        return 1024
    step = 1 << ((total - 1).bit_length() - 3)
    return -(-total // step) * step


def sparse_chunk_from_dense(stack):
    """(k, ...) uint32 dense packed diff stack -> the per-turn
    S-sparse chunk triple (counts (k,) int64, changed-word bitmaps
    (k, nb) uint32, values (Σcounts,) uint32 in ascending word order
    per turn) — the exact layout `compact_scan_diffs` produces on
    device, built host-side in one vectorized pass. Shared by the
    engine and the session manager for chunk-granular emission of
    chunks that ran the plain (un-encoded) diff path."""
    import numpy as _np

    S = _np.ascontiguousarray(stack).reshape(stack.shape[0], -1)
    if S.dtype != _np.uint32:
        S = S.view(_np.uint32)
    k, total = S.shape
    nb = sparse_bitmap_words(total)
    changed = S != 0
    # int32 is ample (counts are bounded by the board's word count)
    # and keeps this host helper inside the kernel-module dtype
    # contract the dtype-drift lint enforces.
    counts = changed.sum(axis=1, dtype=_np.int32)
    values = S[changed]
    padded = (changed if nb * 32 == total
              else _np.pad(changed, ((0, 0), (0, nb * 32 - total))))
    bitmaps = _np.ascontiguousarray(
        _np.packbits(padded, axis=1, bitorder="little")
    ).view(_np.uint32).reshape(k, nb)
    return counts, bitmaps, values


def compact_value_prefix(values, total: int):
    """Fetch (at least) the first `total` words of a compact chunk's
    device value buffer as host uint32 — the bucketed device slice
    (see compact_value_bucket); only this prefix crosses the link."""
    import numpy as _np

    if total <= 0:
        return _np.zeros(0, _np.uint32)
    n = min(int(values.shape[0]), compact_value_bucket(total))
    return _np.ascontiguousarray(_np.asarray(values[:n])).view(_np.uint32)


@dataclasses.dataclass
class BatchStepper:
    """Vmapped execution backend for one session BUCKET
    (gol_tpu.sessions): `capacity` same-shape/same-rule boards stacked
    on a leading axis — uint32 (S, H/32, W) packed words when the grid
    packs, uint8 (S, H, W) otherwise — and stepped by ONE jitted
    dispatch, so S tenants share a single device program and its fixed
    dispatch overhead (ROADMAP open item 3: the measured ~0.333 s fixed
    cost of `engine_512x512` amortizes across the bucket).

    Recompile discipline (the PR 1 recompile lint's dynamic twin,
    pinned by tests/test_sessions.py): `capacity` and the chunk size
    are the ONLY shape-bearing statics. Slot indices are TRACED
    arguments everywhere (`dynamic_index_in_dim` / `.at[i].set`), so
    session create/destroy/checkpoint inside a warm bucket — including
    against padding slots — never builds a new executable. Growing a
    bucket past its capacity is a new BatchStepper and a recompile, by
    design.

    Padding: free slots hold all-zero boards and are stepped like any
    tenant (one program for the whole stack — masking individual slots
    would put a per-slot branch inside the kernel). A zero board stays
    zero under any rule without birth-on-0, which is why the factory
    rejects B0 rules: their padding slots would seethe and saturate the
    shared compact value buffer."""

    name: str
    capacity: int
    height: int
    width: int
    rule: Rule
    packed: bool
    #: packed words per board (0 on the dense fallback) — the decode
    #: space `compact_decode_rows`/`sparse_decode_rows` need.
    total_words: int
    #: list of `capacity` host (H, W) uint8 boards -> device stack
    put_all: Callable
    #: (stack, slot) -> host (H, W) {0,255} uint8 board (slot TRACED)
    fetch_one: Callable
    #: (stack, slot, host (H, W) board) -> stack (slot TRACED)
    set_one: Callable
    #: (stack, slot) -> stack with that slot zeroed (slot TRACED)
    clear_one: Callable
    #: (stack, k) -> (stack, (S,) int32 per-session alive counts)
    step_n: Callable
    #: (stack, k) -> (stack, per-session diff stacks, counts): uint32
    #: (S, k, H/32, W) packed XOR rows when packed, bool (S, k, H, W)
    #: dense masks otherwise — row t of session s is exactly what the
    #: single-board `step_n_with_diffs` would have produced for that
    #: board (same scan body; pinned by bit-equality tests).
    step_n_with_diffs: Callable
    #: (stack, k, total_cap) -> (stack, (S, k, 1+nb) int32 headers,
    #: (S, total_cap) int32 values, counts): the PR 4 variable-length
    #: compact encoding vmapped per session — each session gets its own
    #: [count, bitmap] headers and its own stream-compacted value
    #: buffer, decodable by the existing `compact_decode_rows`, so
    #: per-session chunks feed the wire encoding unchanged. None on the
    #: dense fallback.
    step_n_with_diffs_compact: Optional[Callable] = None
    #: () -> {entry: compiled-executable count}: the jit-cache census
    #: the zero-recompile acceptance test pins (create/step/destroy in
    #: a warm bucket must not move any of these).
    cache_sizes: Optional[Callable] = None

    def offers(self, entry: str) -> bool:
        """Capability probe for the batch plane, sharing ENTRY_TABLE's
        entry names where a bucket field mirrors a Stepper entry (the
        compact diff scan, the diff scan itself) — same contract as
        `Stepper.offers`, so session code probes declaratively too."""
        entry_info(entry)  # unknown entry names are programming errors
        value = getattr(self, entry, None)
        return value is not None and value is not False


def make_batch_stepper(capacity: int, height: int, width: int,
                       rule: Rule | str = LIFE, device=None) -> BatchStepper:
    """Build the vmapped bucket backend: packed SWAR per session when
    the grid packs (the same `bitlife.step_packed` arithmetic as the
    single-board packed stepper, vmapped over the session axis), the
    dense kernel otherwise. Two-state rules only — multi-state
    Generations sessions would need per-bucket plane stacks and belong
    to a later round."""
    import jax.numpy as jnp
    import numpy as _np
    from jax import lax as _lax

    rule = get_rule(rule) if isinstance(rule, str) else rule
    if isinstance(rule, GenRule):
        raise ValueError(
            "session buckets are two-state only (multi-state rules "
            "need per-bucket plane stacks — not yet offered)"
        )
    if 0 in rule.birth:
        raise ValueError(
            f"rule {rule} births on 0 neighbours — empty padding slots "
            "would seethe, so B0 rules cannot share a padded bucket"
        )
    if capacity < 1:
        raise ValueError("bucket capacity must be >= 1")
    dev = device or jax.devices()[0]

    from gol_tpu.ops import bitlife

    if bitlife.packable(height, width):
        step1 = lambda p: bitlife.step_packed(p, rule)  # noqa: E731
        diff1 = lambda old, new: old ^ new              # noqa: E731
        count1 = bitlife.count_packed
        vstep = jax.vmap(step1)

        def put_all(boards):
            if len(boards) != capacity:
                raise ValueError(
                    f"put_all needs {capacity} boards, got {len(boards)}"
                )
            return jax.device_put(
                _np.stack([bitlife.pack_np(b) for b in boards]), dev
            )

        def _host_one(board):
            return bitlife.pack_np(board)

        def _to_host(one):
            return bitlife.unpack_np(_np.asarray(one), height)
    else:
        from gol_tpu.ops import life as _life

        step1 = lambda w: _life.step(w, rule=rule)      # noqa: E731
        diff1 = lambda old, new: old != new             # noqa: E731
        count1 = _life.alive_count
        vstep = jax.vmap(step1)

        def put_all(boards):
            if len(boards) != capacity:
                raise ValueError(
                    f"put_all needs {capacity} boards, got {len(boards)}"
                )
            return jax.device_put(
                _np.stack([_np.asarray(b, _np.uint8) for b in boards]),
                dev,
            )

        def _host_one(board):
            return _np.asarray(board, _np.uint8)

        def _to_host(one):
            return _np.asarray(one)

    @jax.jit
    def _take(stack, slot):
        return _lax.dynamic_index_in_dim(stack, slot, keepdims=False)

    @jax.jit
    def _set(stack, slot, one):
        return stack.at[slot].set(one)

    @jax.jit
    def _clear(stack, slot):
        return stack.at[slot].set(jnp.zeros_like(stack[0]))

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n(stack, k):
        out = _lax.fori_loop(0, max(int(k), 0), lambda _, q: vstep(q),
                             stack)
        return out, jax.vmap(count1)(out)

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n_with_diffs(stack, k):
        return jax.vmap(
            lambda s: _diff_scan(step1, diff1, count1, s, k)
        )(stack)

    packed = bitlife.packable(height, width)
    if packed:
        @functools.partial(jax.jit, static_argnames=("k", "total_cap"))
        def step_n_with_diffs_compact(stack, k, total_cap):
            return jax.vmap(
                lambda s: _compact_scan(step1, diff1, count1, s, k,
                                        total_cap)
            )(stack)
    else:
        step_n_with_diffs_compact = None

    def fetch_one(stack, slot):
        return _to_host(_take(stack, slot))

    def set_one(stack, slot, board):
        b = _np.asarray(board)
        if b.shape != (height, width):
            raise ValueError(f"board shape {b.shape} != {(height, width)}")
        return _set(stack, slot, jax.device_put(_host_one(b), dev))

    jits = {"take": _take, "set": _set, "clear": _clear,
            "step_n": step_n, "diffs": step_n_with_diffs}
    if step_n_with_diffs_compact is not None:
        jits["compact"] = step_n_with_diffs_compact

    def cache_sizes():
        return {name: fn._cache_size() for name, fn in jits.items()
                if hasattr(fn, "_cache_size")}

    return BatchStepper(
        name=("bucket-packed" if packed else "bucket-dense")
        + f"-{capacity}",
        capacity=capacity,
        height=height,
        width=width,
        rule=rule,
        packed=packed,
        total_words=(height // 32) * width if packed else 0,
        put_all=put_all,
        fetch_one=fetch_one,
        set_one=set_one,
        clear_one=lambda stack, slot: _clear(stack, slot),
        step_n=step_n,
        step_n_with_diffs=step_n_with_diffs,
        step_n_with_diffs_compact=step_n_with_diffs_compact,
        cache_sizes=cache_sizes,
    )


def _single_device(rule: Rule, device=None) -> Stepper:
    dev = device or jax.devices()[0]

    return Stepper(
        name="single",
        shards=1,
        put=lambda w: jax.device_put(np.asarray(w, np.uint8), dev),
        fetch=lambda w: np.asarray(w),
        step=lambda w: life.step(w, rule=rule),
        step_n=lambda w, n: life.step_n_counted(w, int(n), rule=rule),
        step_with_diff=lambda w: life.step_with_diff(w, rule=rule),
        alive_count_async=life.alive_count,
        step_n_with_diffs=scan_diffs(
            lambda w: life.step(w, rule=rule),
            lambda old, new: old != new,
            life.alive_count,
        ),
    )


def _packed_state_stepper(name: str, rule: Rule, height: int,
                          step_n_raw, device) -> Stepper:
    """Shared builder for the single-device backends whose device state
    is the packed uint32 board (it stays packed across dispatches —
    pack on `put`, unpack only on `fetch`/diffs). `step_n_raw` is the
    (packed, n) -> packed multi-turn kernel; single turns (step / diff)
    always use the XLA packed step — same arithmetic, no kernel launch
    overhead for k=1."""
    from gol_tpu.ops import bitlife

    _pack, _unpack, _fetch = bitlife.make_codec(height)

    @jax.jit
    def _count(p):
        return bitlife.count_packed(p)

    @functools.partial(jax.jit, static_argnames=("n",))
    def _step_n(p, n):
        p = step_n_raw(p, n)
        return p, bitlife.count_packed(p)

    @jax.jit
    def _step_with_diff(p):
        new = bitlife.step_packed(p, rule)
        # Diff mask unpacked to dense (H, W) bool for cells_from_mask.
        mask = bitlife.unpack(p ^ new, height) != 0
        return new, mask, _count(new)

    return Stepper(
        name=name,
        shards=1,
        put=lambda w: _pack(jax.device_put(np.asarray(w, np.uint8), device)),
        fetch=_fetch,
        step=lambda p: bitlife.step_packed(p, rule),
        step_n=lambda p, n: _step_n(p, int(n)),
        step_with_diff=_step_with_diff,
        alive_count_async=_count,
        # Diffs stay packed: the (k, H/32, W) XOR stack is 8x smaller
        # than dense masks on the host link. (The multi-turn scan uses
        # the XLA packed step even on the pallas backend — bit-exact by
        # the cross-backend tests, and the diff path is link-bound, not
        # kernel-bound.)
        step_n_with_diffs=scan_diffs(
            lambda q: bitlife.step_packed(q, rule),
            lambda old, new: old ^ new,
            bitlife.count_packed,
        ),
        packed_diffs=True,
        step_n_with_diffs_sparse=sparse_scan_diffs(
            lambda q: bitlife.step_packed(q, rule),
            lambda old, new: old ^ new,
            bitlife.count_packed,
        ),
        step_n_with_diffs_compact=compact_scan_diffs(
            lambda q: bitlife.step_packed(q, rule),
            lambda old, new: old ^ new,
            bitlife.count_packed,
        ),
    )


def _single_device_packed(rule: Rule, height: int, device=None,
                          layout: Optional[str] = None) -> Stepper:
    """Bit-packed single-device backend (ops/bitlife.py): XLA fori_loop
    over the SWAR step. ~16x the dense path on TPU (VPU-bound SWAR
    instead of one lane per cell). `layout` selects a registered
    kernel layout from the partition table (partition.LAYOUTS, e.g.
    ``lane-coupled``) for the multi-turn kernel; single turns and the
    diff scans keep the plain SWAR step — bit-exact either way."""
    from gol_tpu.ops import bitlife

    if layout is not None:
        from gol_tpu.parallel import partition

        raw = partition.get_layout(layout)(rule)
        name = f"single-packed-{layout}"
    else:
        raw = lambda p, n: bitlife.step_n_packed_raw(p, n, rule)  # noqa: E731
        name = "single-packed"
    return _packed_state_stepper(
        name, rule, height, raw, device or jax.devices()[0],
    )


def _single_device_pallas_packed(rule: Rule, height: int, width: int,
                                 device=None) -> Stepper:
    """Packed VMEM-resident pallas backend (ops/pallas_bitlife.py):
    multi-turn chunks run as one whole-board kernel when the packed
    working set fits VMEM; boards over it run strip-tiled (32*h turns
    per HBM round trip, halo depth h auto-sized to VMEM), and very wide
    boards run the 2-D tiled kernel — width-tiling keeps the per-op
    shape at the fast 64-row size where the 1-D budget would force thin
    strips (measured 1.93 -> 2.41 Tcells/s at 16384²). Measured
    1.3x-3.6x the XLA packed path on TPU at 512²..8192²
    (BENCH_DETAIL.json)."""
    from gol_tpu.ops import pallas_bitlife

    dev = device or jax.devices()[0]
    interpret = dev.platform != "tpu"  # no mosaic off-TPU
    if pallas_bitlife.fits_pallas_packed(height, width):
        raw = pallas_bitlife.step_n_packed_pallas_raw
    elif pallas_bitlife.fits_pallas_packed_tiled2d(height, width):
        raw = pallas_bitlife.step_n_packed_pallas_tiled2d_raw
    else:
        raw = pallas_bitlife.step_n_packed_pallas_tiled_raw
    return _packed_state_stepper(
        "single-pallas-packed", rule, height,
        lambda p, n: raw(p, n, rule, interpret=interpret),
        dev,
    )


def shard_count(requested: int, height: int, n_devices: int) -> int:
    """Actual shard count for a request: capped by the device count and
    the grid height (a shard must own at least one row), but NOT by
    divisibility — non-dividing counts run the pad/mask uneven halo path
    (parallel/halo.py), so every requested device does work, exactly as
    the reference's row-farm accepts any worker count
    (ref: gol/distributor.go:124-155)."""
    return max(1, min(requested, n_devices, height))


def _single_device_pallas(rule: Rule, device=None) -> Stepper:
    """Whole-board-in-VMEM pallas kernel backend (ops/pallas_life.py).
    Measured equal to XLA's own VMEM-resident loop on TPU and well below
    the packed path — selectable for comparison and as the pallas
    reference implementation, not picked by "auto"."""
    from gol_tpu.ops import pallas_life

    dev = device or jax.devices()[0]
    interpret = dev.platform != "tpu"  # no mosaic off-TPU

    def _step_n(w, n):
        new, count = pallas_life.step_n_counted_pallas(
            w, n, rule=rule, interpret=interpret
        )
        return new, count

    @jax.jit
    def _diff(w, new):
        return w != new

    def _step_with_diff(w):
        new, count = _step_n(w, 1)
        return new, _diff(w, new), count

    return Stepper(
        name="single-pallas",
        shards=1,
        put=lambda w: jax.device_put(np.asarray(w, np.uint8), dev),
        fetch=lambda w: np.asarray(w),
        step=lambda w: pallas_life.step_n_pallas(w, 1, rule=rule,
                                                 interpret=interpret),
        step_n=lambda w, n: _step_n(w, int(n)),
        step_with_diff=_step_with_diff,
        alive_count_async=life.alive_count,
        # Per-turn kernel launches inside a scan would pay the pallas
        # call overhead k times; the diff path scans the (bit-exact)
        # XLA dense step instead — it is link-bound either way.
        step_n_with_diffs=scan_diffs(
            lambda w: life.step(w, rule=rule),
            lambda old, new: old != new,
            life.alive_count,
        ),
    )


def _gens_alive_mask(levels) -> np.ndarray:
    return np.asarray(levels) == life.ALIVE


def _gens_scaffold(device, to_levels):
    """Shared wiring of the two single-device generations builders: the
    bool-mask-passthrough fetch and the CPU serialization — one
    definition so the dense and packed variants cannot drift apart
    here. (Sharded gens runs the ring steppers in parallel/gens_halo.py,
    exactly like the Life family.)"""
    from gol_tpu.parallel.halo import cpu_serializing_sync

    def fetch(arr):
        host = np.asarray(arr)
        if host.dtype == np.bool_:
            return host  # diff masks pass through untranslated
        return to_levels(host)

    return device, fetch, cpu_serializing_sync([device])


def _gens_stepper(rule: GenRule, device) -> Stepper:
    """Generations (B/S/C multi-state) backend — dense uint8 state grid
    (ops/generations.py), single device. Device state holds states
    0..C-1; `put` and `fetch` translate to/from the injective
    gray-level representation the PGM/event layer speaks, so snapshots
    remain complete resumable checkpoints."""
    import jax.numpy as jnp

    from gol_tpu.ops import generations as gens

    sharding, fetch, _sync = _gens_scaffold(
        device, lambda host: gens.levels_from_states(host, rule)
    )

    @jax.jit
    def _count(s):
        return jnp.sum(s == 1, dtype=jnp.int32)

    def put(w):
        return jax.device_put(gens.states_from_levels(w, rule), sharding)

    _snd = scan_diffs(
        lambda s: gens.step_states(s, rule),
        lambda old, new: old != new,
        _count,
    )

    return Stepper(
        name="generations-1",
        shards=1,
        put=put,
        fetch=fetch,
        step=lambda s: _sync(gens.step_n_states(s, 1, rule)),
        step_n=lambda s, k: _sync(
            gens.step_n_counted_states(s, int(k), rule)
        ),
        step_with_diff=lambda s: _sync(gens.step_with_diff_states(s, rule)),
        alive_count_async=lambda s: _sync(_count(s)),
        alive_mask=_gens_alive_mask,
        step_n_with_diffs=lambda s, k: _sync(_snd(s, int(k))),
    )


def _gens_stepper_packed(rule: GenRule, device, height: int,
                         width: int) -> Stepper:
    """Packed generations backend (ops/bitgens.py), single device:
    one-hot dying-state bit-planes, the shared SWAR count machinery on
    the alive plane, aging as a free plane rename — ~the packed Life
    rate for any C. Multi-turn chunks run the pallas kernels
    (ops/pallas_bitgens.py) on TPU — whole-board when every plane fits
    VMEM, strip-tiled with per-plane ghost slabs otherwise — and the
    XLA fori_loop elsewhere."""
    import jax.numpy as jnp

    from gol_tpu.ops import bitgens, bitlife, generations as gens
    from gol_tpu.ops.pallas_bitgens import (
        fits_pallas_gens,
        fits_pallas_gens_tiled,
        prefer_gens_tiled2d,
        step_n_packed_gens_pallas_raw,
        step_n_packed_gens_pallas_tiled2d_raw,
        step_n_packed_gens_pallas_tiled_raw,
    )

    sharding, fetch, _sync = _gens_scaffold(
        device,
        lambda host: gens.levels_from_states(
            bitgens.unpack_states(host, height, rule), rule
        ),
    )
    # The pallas kernels compile only on TPU, like the life kernels:
    # whole-board when every plane fits VMEM, strip-tiled with
    # per-plane ghost slabs otherwise. (Sharded gens runs them INSIDE
    # shard_map via parallel/gens_halo.py's deep blocks.)
    raw_step_n = None
    if device.platform == "tpu":
        if fits_pallas_gens(height, width, rule):
            raw_step_n = functools.partial(
                step_n_packed_gens_pallas_raw, rule=rule
            )
        elif prefer_gens_tiled2d(height, width, rule):
            # Wide boards: width tiling keeps the tile height at the
            # fast op shape the plane-scaled 1-D budget would forbid
            # (only when it actually beats the 1-D plan's height).
            raw_step_n = functools.partial(
                step_n_packed_gens_pallas_tiled2d_raw, rule=rule
            )
        elif fits_pallas_gens_tiled(height, width, rule):
            raw_step_n = functools.partial(
                step_n_packed_gens_pallas_tiled_raw, rule=rule
            )

    def put(w):
        return jax.device_put(
            bitgens.pack_states(gens.states_from_levels(w, rule), rule),
            sharding,
        )

    @jax.jit
    def _count(planes):
        return bitlife.count_packed(planes[0])

    @jax.jit
    def _step(planes):
        return bitgens.step_packed_gens(planes, rule)

    @jax.jit
    def _step_with_diff(planes):
        new = bitgens.step_packed_gens(planes, rule)
        changed = jnp.zeros_like(planes[0])
        for i in range(planes.shape[0]):
            changed = changed | (planes[i] ^ new[i])
        mask = bitlife.unpack(changed, height) != 0
        return new, mask, bitlife.count_packed(new[0])

    if raw_step_n is not None:
        @functools.partial(jax.jit, static_argnames=("k",))
        def _step_n(p, k):
            p = raw_step_n(p, k)
            return p, bitlife.count_packed(p[0])
    else:
        def _step_n(p, k):
            return bitgens.step_n_packed_gens(p, k, rule)

    def _planes_xor(old, new):
        changed = old[0] ^ new[0]
        for i in range(1, old.shape[0]):
            changed = changed | (old[i] ^ new[i])
        return changed

    _snd = scan_diffs(
        lambda p: bitgens.step_packed_gens(p, rule), _planes_xor, _count
    )
    _snd_sparse = sparse_scan_diffs(
        lambda p: bitgens.step_packed_gens(p, rule), _planes_xor, _count
    )
    _snd_compact = compact_scan_diffs(
        lambda p: bitgens.step_packed_gens(p, rule), _planes_xor, _count
    )

    return Stepper(
        name="generations-packed-1",
        shards=1,
        put=put,
        fetch=fetch,
        step=lambda p: _sync(_step(p)),
        step_n=lambda p, k: _sync(_step_n(p, int(k))),
        step_with_diff=lambda p: _sync(_step_with_diff(p)),
        alive_count_async=lambda p: _sync(_count(p)),
        alive_mask=_gens_alive_mask,
        step_n_with_diffs=lambda p, k: _sync(_snd(p, int(k))),
        packed_diffs=True,
        step_n_with_diffs_sparse=lambda p, k, cap: _sync(
            _snd_sparse(p, int(k), int(cap))
        ),
        step_n_with_diffs_compact=lambda p, k, cap: _sync(
            _snd_compact(p, int(k), int(cap))
        ),
    )


def instrument_stepper(s: Stepper) -> Stepper:
    """Wrap a Stepper's dispatch entries with gol_tpu.obs counters and
    wall-time histograms (dataclasses.replace, the checked_stepper
    pattern). Everything here is host-side, per-DISPATCH bookkeeping:
    the wrapped callables still receive and return the exact same
    objects, so dispatch-identity invariants and the pipelined diff
    path see nothing new, and no jitted program changes (the obs-in-jit
    linter check pins that).

    Timing semantics: the histograms record the host-blocking time of
    the dispatch call — true device time on synchronous backends (the
    CPU test mesh serializes; fetch-backed entries sync anyway) and
    enqueue time on async TPU streams; the engine's Timeline remains
    the realizing profiler.

    Halo traffic: when the stepper publishes `halo_cost`, each
    dispatch also bumps gol_tpu_halo_exchanges_total /
    gol_tpu_halo_bytes_total from the block plan the dispatch actually
    compiles — the per-dispatch collective budget docs/PERF.md reasons
    about, now machine-captured."""
    import dataclasses
    import time

    from gol_tpu import obs
    from gol_tpu.obs import tracing
    # Aliased: this module's builders take a `device` PARAMETER, and
    # the obs-in-jit checker treats every binding of an obs-imported
    # name as obs-rooted (name-based on purpose).
    from gol_tpu.obs import device as obs_device

    backend = {"backend": s.name}
    dispatches = {}
    seconds = {}
    # The wrap set comes from the capability table, not a hand-kept
    # tuple — an entry gains instrumentation by declaring a `wrap`
    # shape in ENTRY_TABLE, nowhere else.
    for entry in (e.name for e in ENTRY_TABLE if e.wrap is not None):
        dispatches[entry] = obs.counter(
            "gol_tpu_stepper_dispatches_total",
            "Stepper entry invocations", {**backend, "entry": entry},
        )
        seconds[entry] = obs.histogram(
            "gol_tpu_stepper_dispatch_seconds",
            "Host-blocking seconds per stepper entry call",
            {**backend, "entry": entry},
        )
    halo_exchanges = obs.counter(
        "gol_tpu_halo_exchanges_total",
        "Ring ppermute slab sends dispatched", backend,
    )
    halo_bytes = obs.counter(
        "gol_tpu_halo_bytes_total",
        "Ring halo bytes moved (both directions, all shards)", backend,
    )
    halo_seconds = obs.histogram(
        "gol_tpu_halo_dispatch_seconds",
        "Host-blocking seconds per ring-stepper multi-turn dispatch",
        backend,
    )

    def _charge_halo(world, k, per_turn: bool):
        if s.halo_cost is None:
            return None
        cost = s.halo_cost(world, k, per_turn)
        halo_exchanges.inc(cost["exchanges"])
        halo_bytes.inc(cost["bytes"])
        return cost

    def _span(entry, wall0, dt, cost=None) -> None:
        # One host-side span per stepper entry call on the session
        # timeline (gol_tpu.obs.tracing) — the priced halo traffic
        # rides as args so a merged trace shows where the link budget
        # went without cross-referencing the registry.
        args = {"halo_bytes": cost["bytes"]} if cost else None
        tracing.add_span(f"stepper.{entry}", "stepper", wall0, dt, args)

    def timed(entry, fn):
        disp, hist = dispatches[entry], seconds[entry]

        def wrapper(*args):
            disp.inc()
            wall0 = time.time()
            t0 = time.perf_counter()
            out = fn(*args)
            dt = time.perf_counter() - t0
            hist.observe(dt)
            _span(entry, wall0, dt)
            return out

        return wrapper

    # One cost-model probe per instrumented stepper (CLI-enabled —
    # device.enable_cost_probes): the FIRST `put` publishes the
    # one-turn step program's cost_analysis as gol_tpu_device_cost_*
    # gauges. Probed on the BARE stepper's step (the wrapped entries
    # would drag instrumentation, and the invariant checker's identity
    # state, through the trace), and at PUT time on purpose: the probe
    # is a real AOT compile, and running it inside a dispatch wrapper
    # would land compile seconds in the engine's enqueue-split and
    # first-dispatch latency measurements.
    probed = []

    def _maybe_cost_probe(world) -> None:
        if probed or not obs_device.cost_probes_enabled():
            return
        probed.append(True)
        if jax.process_count() > 1:
            # The SPMD mirror's entries broadcast opcodes to worker
            # processes as a side effect — tracing one for an AOT
            # compile would desync the job for an advisory number.
            return
        obs_device.publish_cost("engine.step", s.step, world)

    _timed_put = timed("put", s.put)

    def put(host_world):
        out = _timed_put(host_world)
        _maybe_cost_probe(out)
        return out

    def step_n(world, k):
        dispatches["step_n"].inc()
        cost = _charge_halo(world, int(k), False)
        wall0 = time.time()
        t0 = time.perf_counter()
        out = s.step_n(world, k)
        dt = time.perf_counter() - t0
        seconds["step_n"].observe(dt)
        if s.halo_cost is not None:
            halo_seconds.observe(dt)
        _span("step_n", wall0, dt, cost)
        # Memory census at the dispatch boundary (rate-limited inside):
        # the HBM/live-buffer watermark tracks every dispatching run.
        obs_device.observe_memory()
        return out

    def _diffy(entry, fn):
        def wrapper(world, k, *rest):
            dispatches[entry].inc()
            cost = _charge_halo(world, int(k), True)
            wall0 = time.time()
            t0 = time.perf_counter()
            out = fn(world, k, *rest)
            dt = time.perf_counter() - t0
            seconds[entry].observe(dt)
            _span(entry, wall0, dt, cost)
            obs_device.observe_memory()
            return out

        return wrapper

    def _one_turn(entry, fn):
        def wrapper(world):
            dispatches[entry].inc()
            cost = _charge_halo(world, 1, True)
            wall0 = time.time()
            t0 = time.perf_counter()
            out = fn(world)
            dt = time.perf_counter() - t0
            seconds[entry].observe(dt)
            _span(entry, wall0, dt, cost)
            return out

        return wrapper

    # The replace set is DERIVED from the capability table: every entry
    # declaring a `wrap` shape gets that wrapper, absent entries stay
    # None — no hand-kept field list to drift from the dataclass.
    wrappers = {"timed": timed, "one_turn": _one_turn, "diffy": _diffy}
    repl: dict = {"put": put, "step_n": step_n}
    for e in ENTRY_TABLE:
        if e.wrap is None or e.name in repl:
            continue
        fn = getattr(s, e.name)
        if fn is not None:
            repl[e.name] = wrappers[e.wrap](e.name, fn)
    return dataclasses.replace(s, **repl)


def make_stepper(
    threads: int = 1,
    height: int = 512,
    width: int = 512,
    rule: Rule | str = LIFE,
    devices: Optional[list] = None,
    backend: str = "auto",
    tile: int = 0,
    mesh: Optional[tuple | str] = None,
    partition_rules: Optional[str] = None,
) -> Stepper:
    """Build the best stepper for the request, wrapped with per-dispatch
    obs instrumentation (unless GOL_TPU_METRICS=0 — the disabled path
    builds the bare stepper, so metrics-off costs literally nothing)
    and with the runtime dispatch-linearity checker when
    GOL_TPU_CHECK_INVARIANTS=1 (cli --check-invariants;
    gol_tpu.analysis.invariants) — host-side identity checks only, so
    the opt-in costs nothing on device. `tile` > 0 selects the
    activity-driven tiled backend (parallel/tiled.py, --tile).
    `mesh` ("RxC" or (rows, cols)) selects the 2-D mesh backends
    (parallel/mesh2d.py, --mesh); `partition_rules` is the operator
    override string for the partition table (--partition-rule)."""
    from gol_tpu import obs

    s = _make_stepper(threads, height, width, rule, devices, backend,
                      tile, mesh, partition_rules)
    if obs.enabled():
        s = instrument_stepper(s)
    from gol_tpu.analysis.invariants import checked_stepper, invariants_enabled

    if invariants_enabled():
        s = checked_stepper(s)
    return s


def _make_stepper(
    threads: int = 1,
    height: int = 512,
    width: int = 512,
    rule: Rule | str = LIFE,
    devices: Optional[list] = None,
    backend: str = "auto",
    tile: int = 0,
    mesh: Optional[tuple | str] = None,
    partition_rules: Optional[str] = None,
) -> Stepper:
    """Build the best stepper for the request (the dispatch analog of
    ref: gol/distributor.go:93,116 picking serial vs row-farm).

    `backend` picks the kernel family: "auto" (bit-packed when the grid
    allows, else dense), or an explicit "packed" / "dense" / "pallas".
    Sharded runs (threads > 1 with multiple devices) use the packed
    ring-halo path when every strip is a whole number of 32-row words,
    the dense ring-halo path otherwise ("dense" forces the latter;
    "pallas" applies to single-device only). `tile` > 0 selects the
    activity-driven tiled backend instead: the dispatch SET (which
    macro-tiles a change's light cone touched) is the parallelism
    axis there, so `threads` does not apply and the board stays
    host-resident (boards past HBM)."""
    if backend not in BACKENDS:
        raise ValueError(f"backend must be one of {BACKENDS}, got {backend!r}")
    rule = get_rule(rule) if isinstance(rule, str) else rule
    multiprocess = devices is None and jax.process_count() > 1
    layout = None
    if partition_rules:
        from gol_tpu.parallel import partition

        # Parse once up front: a bad override string fails the build,
        # not the first dispatch; `layout=NAME` rides to the
        # single-device packed path below.
        _, layout = partition.parse_overrides(partition_rules)
    if mesh is not None:
        from gol_tpu.parallel import partition

        rows, cols = (
            partition.parse_mesh(mesh) if isinstance(mesh, str)
            else (int(mesh[0]), int(mesh[1]))
        )
        if rows * cols > 1:
            # An explicit mesh selects the 2-D family (parallel/
            # mesh2d.py) — including the degenerate 1xN / Nx1 shapes,
            # which collapse to rings bit-exactly; `threads`-driven
            # requests keep the tuned deep-halo 1-D rings.
            if tile:
                raise ValueError(
                    "--mesh and --tile are exclusive (the tiled "
                    "backend's dispatch set is its parallelism axis)"
                )
            if backend not in ("auto", "packed"):
                raise ValueError(
                    f"mesh backends are packed-only (backend auto/"
                    f"packed, not {backend!r})"
                )
            from gol_tpu.parallel.mesh2d import (
                mesh2d_packed_gens_stepper,
                mesh2d_packed_stepper,
            )

            if multiprocess:
                from gol_tpu.parallel.multihost import round_robin_devices

                devs = round_robin_devices()
            else:
                devs = devices if devices is not None else jax.devices()
            need = rows * cols
            if len(devs) < need:
                raise ValueError(
                    f"mesh {rows}x{cols} needs {need} devices, "
                    f"have {len(devs)}"
                )
            if isinstance(rule, GenRule):
                s = mesh2d_packed_gens_stepper(
                    rule, devs[:need], height, width, rows, cols,
                    partition_rules,
                )
            else:
                s = mesh2d_packed_stepper(
                    rule, devs[:need], height, width, rows, cols,
                    partition_rules,
                )
            from gol_tpu.parallel import multihost

            if multihost.is_multiprocess_mesh(devs[:need]):
                if multihost.is_coordinator():
                    return multihost.spmd_stepper(s)
            return s
    if tile:
        if multiprocess:
            raise ValueError(
                "tiled stepping is single-process (the dispatch set is "
                "its parallelism axis; multi-chip composes at the "
                "partition-rule layer, not here)"
            )
        from gol_tpu.parallel.tiled import tiled_stepper

        devs = devices if devices is not None else jax.devices()
        return tiled_stepper(rule, height, width, tile,
                             device=devs[0])
    if isinstance(rule, GenRule):
        # Multi-state rules ride the SAME distribution machinery as the
        # Life family (VERDICT r3 Missing #1): one-hot bit-planes
        # (packed SWAR, ~the Life rate) on whole-word strips, the dense
        # state ring — balanced-split for non-divisors — otherwise, and
        # the SPMD dispatch mirror across processes. No request is
        # silently clamped.
        from gol_tpu.ops.bitgens import packable_gens

        if backend not in ("auto", "dense", "packed"):
            raise ValueError(
                f"generations rules support backend auto/dense/packed, "
                f"not {backend!r}"
            )
        if multiprocess:
            from gol_tpu.parallel.multihost import round_robin_devices

            devs = round_robin_devices()
        else:
            devs = devices if devices is not None else jax.devices()
        k = shard_count(threads, height, len(devs))
        if multiprocess and k < jax.process_count():
            raise ValueError(
                f"threads={threads} shards cannot span the "
                f"{jax.process_count()}-process job — every process must "
                "own at least one shard (raise -t or shrink the job)"
            )
        if backend == "packed" and not packable_gens(height, width):
            raise ValueError(f"grid height {height} is not packable")
        # One-hot planes cost (C-1)/8 bytes per cell vs the dense
        # grid's 1 — memory crosses over at C=9, so "auto" keeps the
        # packed path to rules where it is strictly smaller AND faster;
        # higher C stays packed only on explicit request.
        want_packed = backend == "packed" or (
            backend == "auto" and rule.states <= 8
        )
        if k > 1:
            from gol_tpu.parallel.gens_halo import (
                gens_sharded_stepper,
                packable_gens_sharded,
                packable_gens_sharded_uneven,
                packed_gens_sharded_stepper,
                packed_gens_sharded_stepper_uneven,
            )

            if backend == "packed" and not (
                packable_gens_sharded(height, k)
                or packable_gens_sharded_uneven(height, k)
            ):
                raise ValueError(
                    f"grid height {height} over {k} shards is not packable "
                    f"(each shard must own at least one whole 32-row word)"
                )
            if want_packed and packable_gens_sharded(height, k):
                s = packed_gens_sharded_stepper(rule, devs[:k], height)
            elif want_packed and packable_gens_sharded_uneven(height, k):
                # Non-divisors keep the packed planes via the balanced
                # split (family parity with the Life ring, r5).
                s = packed_gens_sharded_stepper_uneven(rule, devs[:k], height)
            else:
                s = gens_sharded_stepper(rule, devs[:k], height)
            from gol_tpu.parallel import multihost

            if multihost.is_multiprocess_mesh(devs[:k]):
                if multihost.is_coordinator():
                    return multihost.spmd_stepper(s)
            return s
        if want_packed and packable_gens(height, width):
            return _gens_stepper_packed(rule, devs[0], height, width)
        return _gens_stepper(rule, devs[0])
    if multiprocess:
        # Round-robin across processes so the k-shard prefix spans every
        # host; process-grouped order would leave whole hosts silently
        # idle whenever k fits on the coordinator.
        from gol_tpu.parallel.multihost import round_robin_devices

        devs = round_robin_devices()
    else:
        devs = devices if devices is not None else jax.devices()
    k = shard_count(threads, height, len(devs))
    if multiprocess and k < jax.process_count():
        raise ValueError(
            f"threads={threads} shards cannot span the "
            f"{jax.process_count()}-process job — every process must own "
            "at least one shard (raise -t or shrink the job)"
        )
    if k > 1:
        from gol_tpu.parallel.halo import sharded_stepper
        from gol_tpu.parallel.packed_halo import (
            packable_sharded,
            packable_sharded_uneven,
            packed_sharded_stepper,
            packed_sharded_stepper_uneven,
        )

        # Explicit impossible requests fail loudly, like single-device.
        if backend in ("pallas", "pallas-packed"):
            raise ValueError(f"{backend} backend is single-device only")
        if backend == "packed" and not (
            packable_sharded(height, k) or packable_sharded_uneven(height, k)
        ):
            raise ValueError(
                f"grid height {height} over {k} shards is not packable "
                f"(each shard must own at least one whole 32-row word)"
            )
        if backend != "dense" and packable_sharded(height, k):
            s = packed_sharded_stepper(rule, devs[:k], height)
        elif backend != "dense" and packable_sharded_uneven(height, k):
            # Non-divisor counts: the word-granular balanced split keeps
            # the SWAR ring + deep halos (VERDICT r4 Missing #1).
            s = packed_sharded_stepper_uneven(rule, devs[:k], height)
        else:
            s = sharded_stepper(rule, devs[:k], height)
        from gol_tpu.parallel import multihost

        if multihost.is_multiprocess_mesh(devs[:k]):
            # The mesh spans processes: the coordinator's dispatches must
            # be mirrored on every worker (SPMD contract). Workers get
            # the inner stepper and replay via spmd_worker_loop.
            if multihost.is_coordinator():
                return multihost.spmd_stepper(s)
        return s

    from gol_tpu.ops.bitlife import packable
    from gol_tpu.ops.pallas_bitlife import (
        fits_pallas_packed,
        fits_pallas_packed_tiled,
    )
    from gol_tpu.ops.pallas_life import fits_pallas

    pallas_packed_ok = (fits_pallas_packed(height, width)
                        or fits_pallas_packed_tiled(height, width))
    on_tpu = devs[0].platform == "tpu"  # mosaic compiles only there;
    # elsewhere the kernels run in (slow) interpreter mode, so "auto"
    # never picks them off-TPU.
    if backend == "pallas-packed" or (
        backend == "auto" and on_tpu and pallas_packed_ok
    ):
        if not pallas_packed_ok:
            raise ValueError(
                f"grid {height}x{width} does not fit the packed pallas "
                "kernels (needs whole 32-row words, rows % 8, width % 128)"
            )
        return _single_device_pallas_packed(rule, height, width, devs[0])
    if backend == "packed" or (backend == "auto" and packable(height, width)):
        if not packable(height, width):
            raise ValueError(f"grid {height}x{width} is not packable")
        return _single_device_packed(rule, height, devs[0], layout=layout)
    if backend == "pallas":
        if not fits_pallas(height, width):
            raise ValueError(f"grid {height}x{width} does not fit the "
                             "pallas VMEM kernel")
        return _single_device_pallas(rule, devs[0])
    return _single_device(rule, devs[0])
