"""Row-strip sharding with ring halo exchange — the TPU-native flagship.

The reference's coursework spec calls for workers that own horizontal
board strips and exchange *only their edge rows* with ring neighbours
instead of resyncing the whole board through a central node
(ref: README.md:195-199,239-245 — specified as the halo-exchange
extension, never implemented; the in-repo row-farm dodges it by giving
every worker the whole board, ref: gol/distributor.go:318-347).

Here it is, done the TPU way: the grid is sharded into contiguous row
strips over a 1-D device mesh via `shard_map`; each step every shard
sends its first/last row to its ring neighbours with `lax.ppermute` —
two one-row transfers per shard per turn over ICI — computes the
stencil on its strip extended by the two halo rows, and applies the B/S
rule. The torus wraps naturally because the ring is closed: shard 0's
upper neighbour is shard n-1, which owns the bottom rows of the grid.

Multi-turn chunks keep the whole loop (halos included) on device inside
`lax.fori_loop` — zero host round-trips between turns. The global alive
count is a local reduction + `psum` (the distributed analog of
ref: gol/distributor.go:420-432).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gol_tpu.models.rules import Rule
from gol_tpu.ops.life import apply_rule, from_bits, to_bits

AXIS = "rows"


def ring_perms(n: int) -> tuple[list, list]:
    """(down, up) permutation pairs of the closed n-ring — the single
    definition of ring orientation for every halo path."""
    down = [(i, (i + 1) % n) for i in range(n)]
    up = [(i, (i - 1) % n) for i in range(n)]
    return down, up


def edge_exchange(p: jax.Array, axis: str = AXIS):
    """ppermute this shard's first/last slice rows around the ring;
    returns (row owned by the shard above, row owned by the shard
    below). Works for dense bit rows and packed word rows alike."""
    down, up = ring_perms(lax.axis_size(axis))
    above_last = lax.ppermute(p[-1:], axis, down)
    below_first = lax.ppermute(p[:1], axis, up)
    return above_last, below_first


def cpu_serializing_sync(devices: list):
    """On the CPU backend (virtual test meshes), concurrent in-flight
    programs containing collectives starve each other's rendezvous when
    host cores are scarce — intra-program collectives are fine, so the
    fix is to keep at most one program in flight by blocking on each
    dispatch. Real TPU streams don't have this hazard; dispatch stays
    fully async there."""
    if devices[0].platform == "cpu":
        return jax.block_until_ready

    def _passthrough(x):
        return x

    return _passthrough


def halo_step_bits(block: jax.Array, rule: Rule, axis: str = AXIS) -> jax.Array:
    """One turn on a local {0,1} row strip, exchanging one-row halos with
    ring neighbours over `axis`. Runs inside `shard_map`."""
    # My bottom row is the upper halo of the shard below me; my top row is
    # the lower halo of the shard above me. Closed ring => toroidal wrap.
    halo_top, halo_bottom = edge_exchange(block, axis)
    ext = jnp.concatenate([halo_top, block, halo_bottom], axis=0)
    # Vertical 3-sum over the extended strip (valid region = my rows),
    # then horizontal toroidal 3-sum, minus centre — same separable
    # kernel as ops.life.neighbour_counts.
    v = ext[:-2] + ext[1:-1] + ext[2:]
    counts = v + jnp.roll(v, 1, 1) + jnp.roll(v, -1, 1) - block
    return apply_rule(block, counts, rule)


def sharded_stepper(rule: Rule, devices: list, height: int):
    """Build a Stepper whose world lives row-sharded across `devices`."""
    from gol_tpu.parallel.stepper import Stepper

    n = len(devices)
    if height % n != 0:
        raise ValueError(f"height {height} not divisible by {n} shards")
    mesh = Mesh(np.asarray(devices), (AXIS,))
    sharding = NamedSharding(mesh, P(AXIS, None))
    spec = P(AXIS, None)

    @jax.jit
    def step(world):
        @functools.partial(jax.shard_map, mesh=mesh, in_specs=spec, out_specs=spec)
        def _one(block):
            return from_bits(halo_step_bits(to_bits(block), rule))

        return _one(world)

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n(world, k):
        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=spec, out_specs=(spec, P())
        )
        def _many(block):
            bits = to_bits(block)
            bits = lax.fori_loop(0, k, lambda _, b: halo_step_bits(b, rule), bits)
            # Local reduction + psum over the ring — the distributed
            # alive count (ref: gol/distributor.go:420-432), fused into
            # the same program as the turns.
            count = lax.psum(jnp.sum(bits, dtype=jnp.int32), AXIS)
            return from_bits(bits), count

        return _many(world)

    @jax.jit
    def step_with_diff(world):
        new, count = step_n(world, 1)
        return new, world != new, count

    @jax.jit
    def count(world):
        return jnp.sum(world != 0, dtype=jnp.int32)

    _sync = cpu_serializing_sync(devices)

    return Stepper(
        name=f"halo-ring-{n}",
        shards=n,
        put=lambda w: jax.device_put(np.asarray(w, np.uint8), sharding),
        fetch=lambda w: np.asarray(w),
        step=lambda w: _sync(step(w)),
        step_n=lambda w, k: _sync(step_n(w, int(k))),
        step_with_diff=lambda w: _sync(step_with_diff(w)),
        alive_count_async=lambda w: _sync(count(w)),
    )
