"""Row-strip sharding with ring halo exchange — the TPU-native flagship.

The reference's coursework spec calls for workers that own horizontal
board strips and exchange *only their edge rows* with ring neighbours
instead of resyncing the whole board through a central node
(ref: README.md:195-199,239-245 — specified as the halo-exchange
extension, never implemented; the in-repo row-farm dodges it by giving
every worker the whole board, ref: gol/distributor.go:318-347).

Here it is, done the TPU way: the grid is sharded into contiguous row
strips over a 1-D device mesh via `shard_map`; each step every shard
sends its first/last row to its ring neighbours with `lax.ppermute` —
two one-row transfers per shard per turn over ICI — computes the
stencil on its strip extended by the two halo rows, and applies the B/S
rule. The torus wraps naturally because the ring is closed: shard 0's
upper neighbour is shard n-1, which owns the bottom rows of the grid.

Multi-turn chunks keep the whole loop (halos included) on device inside
`lax.fori_loop` — zero host round-trips between turns. The global alive
count is a local reduction + `psum` (the distributed analog of
ref: gol/distributor.go:420-432).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.models.rules import Rule
from gol_tpu.ops.life import apply_rule, from_bits, step_bits, to_bits
from gol_tpu.parallel import partition

AXIS = partition.AXIS_ROWS

#: Deep-halo depth cap for the dense ring: exchange K edge rows once,
#: step K exact turns locally (validity shrinks one row per turn into
#: the ghosts), slice the strip back out — K× fewer ring collectives
#: for fused multi-turn dispatches. Same construction as the packed
#: path's one-ghost-word blocks (parallel/packed_halo.py), with K
#: bounded by the strip height (each ghost must come whole from ONE
#: ring neighbour).
DEEP_ROWS = 16


def ring_perms(n: int) -> tuple[list, list]:
    """(down, up) permutation pairs of the closed n-ring — the single
    definition of ring orientation for every halo path."""
    down = [(i, (i + 1) % n) for i in range(n)]
    up = [(i, (i - 1) % n) for i in range(n)]
    return down, up


def edge_exchange(p: jax.Array, axis: str = AXIS, depth: int = 1):
    """ppermute this shard's first/last `depth` slice rows around the
    ring; returns (rows owned by the shard above, rows owned by the
    shard below). Works for dense bit rows and packed word rows alike —
    the single definition of ring orientation for per-turn halos
    (depth=1) and deep-halo ghosts (depth=K) in both representations."""
    down, up = ring_perms(lax.axis_size(axis))
    above_last = lax.ppermute(p[-depth:], axis, down)
    below_first = lax.ppermute(p[:depth], axis, up)
    return above_last, below_first


def cpu_serializing_sync(devices: list):
    """On the CPU backend (virtual test meshes), concurrent in-flight
    programs containing collectives starve each other's rendezvous when
    host cores are scarce — intra-program collectives are fine, so the
    fix is to keep at most one program in flight by blocking on each
    dispatch. Real TPU streams don't have this hazard; dispatch stays
    fully async there."""
    if devices[0].platform == "cpu":
        return jax.block_until_ready

    def _passthrough(x):
        return x

    return _passthrough


def halo_step_bits(block: jax.Array, rule: Rule, axis: str = AXIS) -> jax.Array:
    """One turn on a local {0,1} row strip, exchanging one-row halos with
    ring neighbours over `axis`. Runs inside `shard_map`."""
    # My bottom row is the upper halo of the shard below me; my top row is
    # the lower halo of the shard above me. Closed ring => toroidal wrap.
    halo_top, halo_bottom = edge_exchange(block, axis)
    ext = jnp.concatenate([halo_top, block, halo_bottom], axis=0)
    # Vertical 3-sum over the extended strip (valid region = my rows),
    # then horizontal toroidal 3-sum, minus centre — same separable
    # kernel as ops.life.neighbour_counts.
    v = ext[:-2] + ext[1:-1] + ext[2:]
    counts = v + jnp.roll(v, 1, 1) + jnp.roll(v, -1, 1) - block
    return apply_rule(block, counts, rule)


def halo_step_bits_uneven(
    block: jax.Array, rule: Rule, n: int, height: int, axis: str = AXIS
) -> jax.Array:
    """One turn on a local {0,1} row strip when the grid height does not
    divide the shard count (SURVEY §7 'pad/mask under uneven shards').

    Balanced layout: every shard's physical block is S = ceil(H/n) rows;
    shard i really owns S rows if i < H mod n, else S-1 (the classic
    balanced split — no shard idles, unlike padding the tail). The
    shard-local deviations from the even path, driven by
    `lax.axis_index`:

    - each shard sends its last *real* row (index real-1, not S-1) down
      the ring as its neighbour's upper halo;
    - the wrap row arriving from below is spliced in directly after the
      last real row, so the seam stencil sees the true ring neighbour
      instead of padding;
    - after the rule combine, padding rows are forced dead (they border
      live cells at the seam, so births could otherwise appear there).
    """
    S = block.shape[0]
    r = height % n  # > 0: the uneven case
    idx = lax.axis_index(axis)
    real = jnp.where(idx < r, S, S - 1)
    down, up = ring_perms(n)
    send_down = lax.dynamic_slice(
        block, (real - 1, jnp.int32(0)), (1, block.shape[1])
    )
    halo_top = lax.ppermute(send_down, axis, down)
    halo_bottom = lax.ppermute(block[:1], axis, up)
    ext = jnp.concatenate([halo_top, block, halo_bottom], axis=0)
    ext = lax.dynamic_update_slice(ext, halo_bottom, (real + 1, jnp.int32(0)))
    v = ext[:-2] + ext[1:-1] + ext[2:]
    counts = v + jnp.roll(v, 1, 1) + jnp.roll(v, -1, 1) - block
    new = apply_rule(block, counts, rule)
    row_ids = lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    return jnp.where(row_ids < real, new, jnp.zeros_like(new))


def dense_ring_halo_cost(n: int, deep: int):
    """Host-side ring-traffic accounting for a dense ring of `n`
    shards with deep-halo depth `deep` — the `Stepper.halo_cost` hook
    (pure arithmetic over the SAME block plan step_n compiles; bytes
    are uint8 bit-rows, both directions, summed over all shards).
    `per_turn=True` prices the scanned diff paths, which ppermute one
    edge row per turn."""

    def halo_cost(world, k, per_turn: bool = False) -> dict:
        k = max(int(k), 0)
        w = int(world.shape[-1])
        if per_turn or deep < 2:
            sends, rows = 2 * k, 2 * k
        else:
            blocks, rem = divmod(k, deep)
            sends = 2 * (blocks + rem)
            rows = 2 * (blocks * deep + rem)
        return {"exchanges": sends * n, "bytes": rows * w * n}

    return halo_cost


def _ring_stepper(name: str, devices: list, step_n, put, fetch,
                  fetch_diffs=None, halo_cost=None):
    """Common wiring of both dense ring builders: single-turn wrappers
    derived from `step_n`, the async count, CPU-mesh serialization, and
    the Stepper assembly — one definition, so the even (deep-halo) and
    uneven (balanced-split) variants cannot drift apart here."""
    from gol_tpu.parallel.stepper import Stepper, scan_diffs

    @jax.jit
    def step(world):
        return step_n(world, 1)[0]

    @jax.jit
    def step_with_diff(world):
        new, count = step_n(world, 1)
        return new, world != new, count

    @jax.jit
    def count(world):
        return jnp.sum(world != 0, dtype=jnp.int32)

    # Per-turn halos inside one scanned program: the unused per-turn
    # psum count inside step_n(·, 1) is dead code XLA prunes. Diffs
    # stack sharded along their row axis; the engine gathers once.
    _snd = scan_diffs(lambda w: step_n(w, 1)[0],
                      lambda old, new: old != new, count)

    _sync = cpu_serializing_sync(devices)

    return Stepper(
        name=name,
        shards=len(devices),
        put=put,
        fetch=fetch,
        step=lambda w: _sync(step(w)),
        step_n=lambda w, k: _sync(step_n(w, int(k))),
        step_with_diff=lambda w: _sync(step_with_diff(w)),
        alive_count_async=lambda w: _sync(count(w)),
        step_n_with_diffs=lambda w, k: _sync(_snd(w, int(k))),
        fetch_diffs=fetch_diffs,
        halo_cost=halo_cost,
    )


def sharded_stepper(rule: Rule, devices: list, height: int):
    """Build a Stepper whose world lives row-sharded across `devices`.

    Any (height, shard-count) pair is accepted: when `height % n != 0`
    every shard still owns an equal ceil(height/n)-row block, with the
    balanced split's short shards (index >= height % n) carrying one
    dead padding row each, kept dead by `halo_step_bits_uneven` — so
    the ring program stays SPMD and every device works, the analog of
    the reference's row-farm accepting any worker count
    (ref: gol/distributor.go:124-155)."""
    n = len(devices)
    if height % n != 0:
        return _sharded_stepper_uneven(rule, devices, height)
    table = partition.table_for("dense_ring")
    mesh = partition.ring_mesh(devices)
    spec = table.resolve("world", ndim=2)
    sharding = partition.named_sharding(mesh, spec)

    deep = min(DEEP_ROWS, height // n)

    def deep_block(bits):
        """One K-row exchange, K exact local turns (see DEEP_ROWS)."""
        top_ghost, bottom_ghost = edge_exchange(bits, AXIS, depth=deep)
        ext = jnp.concatenate([top_ghost, bits, bottom_ghost], axis=0)
        # Plain toroidal stepping: the wrap only corrupts rows whose
        # validity the one-row-per-turn shrink already wrote off.
        ext = lax.fori_loop(0, deep, lambda _, b: step_bits(b, rule), ext)
        return ext[deep:-deep]

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n(world, k):
        blocks, rem = divmod(max(k, 0), deep)

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=spec,
            out_specs=(spec, partition.REPLICATED),
        )
        def _many(block):
            bits = to_bits(block)
            bits = lax.fori_loop(0, blocks, lambda _, b: deep_block(b), bits)
            bits = lax.fori_loop(
                0, rem, lambda _, b: halo_step_bits(b, rule), bits
            )
            # Local reduction + psum over the ring — the distributed
            # alive count (ref: gol/distributor.go:420-432), fused into
            # the same program as the turns.
            count = lax.psum(jnp.sum(bits, dtype=jnp.int32), AXIS)
            return from_bits(bits), count

        return _many(world)

    from gol_tpu.parallel.multihost import spmd_fetch, spmd_put

    return _ring_stepper(
        f"halo-ring-{n}", devices, step_n,
        put=lambda w: spmd_put(sharding, np.asarray(w, np.uint8)),
        fetch=spmd_fetch,
        fetch_diffs=spmd_fetch,
        halo_cost=dense_ring_halo_cost(n, deep),
    )


def deep_block_uneven(bits, rule: Rule, d: int, real, n: int,
                      step_fn=None):
    """One d-row ghost exchange, d exact local turns on a balanced
    split strip (real rows at the top of an S-row block, padding
    below). The packed ring's balanced deep-block construction at
    bit-row granularity: the downward-sent slab starts at real-d, the
    below-ghost is spliced directly after the last real row so the
    light cone sees contiguous rows, and padding is re-zeroed after
    the slice-out. `step_fn(b)` is the plain toroidal single-turn
    kernel (defaults to the Life step; the gens ring injects its
    own)."""
    step_fn = step_fn or (lambda b: step_bits(b, rule))
    S = bits.shape[0]
    down, up = ring_perms(n)
    send_down = lax.dynamic_slice(
        bits, (real - d, jnp.int32(0)), (d, bits.shape[1])
    )
    above = lax.ppermute(send_down, AXIS, down)
    below = lax.ppermute(bits[:d], AXIS, up)
    ext = jnp.concatenate([above, bits, jnp.zeros_like(bits[:d])], axis=0)
    ext = lax.dynamic_update_slice(ext, below, (d + real, jnp.int32(0)))
    ext = lax.fori_loop(0, d, lambda _, b: step_fn(b), ext)
    out = ext[d : d + S]
    row_ids = lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    return jnp.where(row_ids < real, out, jnp.zeros_like(out))


def balanced_deep_step_n(mesh, spec, n: int, strip: int, rem: int,
                         deep: int, deep_step, per_turn, count_local,
                         to_rep=None, from_rep=None):
    """ONE builder for the balanced dense splits' fused step_n — deep-
    halo blocks (one d-row ghost exchange per d exact local turns of
    the plain toroidal `deep_step`) plus a per-turn `per_turn` tail —
    shared by the Life and Generations uneven rings so the dispatch
    policy (the deep>=2 guard, the per-shard real-row formula, the
    block/tail split) cannot drift between the families (the
    _ring_stepper convention applied here)."""
    to_rep = to_rep or (lambda b: b)
    from_rep = from_rep or (lambda b: b)

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n(world, k):
        blocks, rem_t = divmod(max(k, 0), deep) if deep >= 2 else (0, k)

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=spec,
            out_specs=(spec, partition.REPLICATED),
        )
        def _many(block):
            idx = lax.axis_index(AXIS)
            real_rows = jnp.where(idx < rem, strip, strip - 1)
            b = to_rep(block)
            b = lax.fori_loop(
                0, blocks,
                lambda _, q: deep_block_uneven(
                    q, None, deep, real_rows, n, step_fn=deep_step
                ),
                b,
            )
            b = lax.fori_loop(0, rem_t, lambda _, q: per_turn(q), b)
            # Padding is kept dead by the steps, so the plain local
            # reduction + psum is already the exact global count.
            count = lax.psum(count_local(b), AXIS)
            return from_rep(b), count

        return _many(world)

    return step_n


def _sharded_stepper_uneven(rule: Rule, devices: list, height: int):
    """The `height % n != 0` variant of `sharded_stepper`: device state
    is a (n * ceil(H/n), W) array holding each shard's real rows at the
    top of its strip (balanced split: shard i owns ceil rows if
    i < H mod n, else floor). `put`/`fetch` scatter/gather the real
    rows, so callers never see the padding. Fused multi-turn dispatches
    run deep-halo blocks (one d-row exchange per d local turns, d
    capped at the shortest shard) instead of per-turn ppermutes (r5:
    the dense rings joined the communication-avoiding story, VERDICT
    r4 Weak #3)."""
    n = len(devices)
    strip = -(-height // n)  # ceil
    rem = height % n
    real = [strip if i < rem else strip - 1 for i in range(n)]
    offsets = np.concatenate([[0], np.cumsum(real)])
    table = partition.table_for("dense_ring")
    mesh = partition.ring_mesh(devices)
    spec = table.resolve("world", ndim=2)
    sharding = partition.named_sharding(mesh, spec)
    deep = min(DEEP_ROWS, strip - 1)  # every ghost from ONE neighbour

    step_n = balanced_deep_step_n(
        mesh, spec, n, strip, rem, deep,
        deep_step=lambda b: step_bits(b, rule),
        per_turn=lambda b: halo_step_bits_uneven(b, rule, n, height),
        count_local=lambda b: jnp.sum(b, dtype=jnp.int32),
        to_rep=to_bits, from_rep=from_bits,
    )

    from gol_tpu.parallel.multihost import spmd_fetch, spmd_put

    def put(w):
        host = np.asarray(w, np.uint8)
        padded = np.zeros((n * strip, host.shape[1]), np.uint8)
        for i in range(n):
            padded[i * strip : i * strip + real[i]] = (
                host[offsets[i] : offsets[i + 1]]
            )
        return spmd_put(sharding, padded)

    def fetch(a):
        host = spmd_fetch(a)
        return np.concatenate(
            [host[i * strip : i * strip + real[i]] for i in range(n)]
        )

    def fetch_diffs(d):
        # (k, n*strip, W) padded diff stack -> (k, H, W): padding rows
        # are dead on both sides of every turn, but their positions must
        # still be cut out so row indices map to global y coordinates.
        host = spmd_fetch(d)
        return np.concatenate(
            [host[:, i * strip : i * strip + real[i]] for i in range(n)],
            axis=1,
        )

    return _ring_stepper(f"halo-ring-uneven-{n}", devices, step_n, put,
                         fetch, fetch_diffs,
                         halo_cost=dense_ring_halo_cost(n, deep))
