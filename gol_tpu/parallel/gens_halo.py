"""Row-strip sharding for the Generations (B/S/C) family — the Life
ring machinery (parallel/halo.py, parallel/packed_halo.py) applied to
multi-state boards, so the whole model family rides the whole
distribution story (VERDICT r3 Missing #1; ref worker contract: any
thread count works and every worker does work,
ref: gol/distributor.go:124-155, swept by gol_test.go:16-31).

Key physics: a Generations cell's next state depends on its OWN state
(which dying plane it sits in — purely local) and on the count of
state-1 (alive) neighbours only (ops/generations.py:37-47). So:

- per-turn halos exchange just ONE row (dense) / word-row (packed) of
  state, exactly like Life — dying cells travel with the state rows but
  only the alive bits feed the stencil;
- communication-avoiding deep blocks (packed path) ghost-extend ALL
  planes by h word-rows per side (a ghost cell's multi-turn evolution
  needs its age), then step 32·h exact local turns per exchange with
  the same one-row-per-turn validity shrink as Life — and those local
  turns run the pallas gens kernels (ops/pallas_bitgens.py) inside
  shard_map on TPU, the same fast-path composition as
  packed_halo.local_block_mode.

Shard-count policy mirrors Life exactly: whole-word strips run the
packed ring; anything else — including NON-DIVISOR counts — runs the
dense ring with the balanced split (ceil/floor real rows per shard,
padding rows forced dead), so no requested device ever idles and no
request is silently clamped.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.models.rules import GenRule
from gol_tpu.ops import bitgens, bitlife, generations as gens
from gol_tpu.ops.bitlife import WORD
from gol_tpu.ops.life import count_in
from gol_tpu.parallel import partition
from gol_tpu.parallel.halo import (
    AXIS,
    cpu_serializing_sync,
    edge_exchange,
    ring_perms,
)


def _gens_combine(state: jax.Array, counts: jax.Array,
                  rule: GenRule) -> jax.Array:
    """The Generations state update given alive-neighbour counts — the
    single definition shared by both sharded dense variants; must match
    ops/generations.step_states bit-for-bit."""
    born = (state == 0) & count_in(counts, rule.birth)
    stays = (state == 1) & count_in(counts, rule.survive)
    aged = jnp.where(state > 0, state + 1, state)
    aged = jnp.where(aged >= rule.states, 0, aged).astype(jnp.uint8)
    return jnp.where(born | stays, jnp.uint8(1), aged)


def halo_step_states(block: jax.Array, rule: GenRule,
                     axis: str = AXIS) -> jax.Array:
    """One Generations turn on a local uint8 state strip, one-row halos
    over `axis` (the multi-state analog of halo.halo_step_bits)."""
    halo_top, halo_bottom = edge_exchange(block, axis)
    ext = jnp.concatenate([halo_top, block, halo_bottom], axis=0)
    ext_alive = (ext == 1).astype(jnp.uint8)
    v = ext_alive[:-2] + ext_alive[1:-1] + ext_alive[2:]
    counts = (
        v + jnp.roll(v, 1, 1) + jnp.roll(v, -1, 1)
        - (block == 1).astype(jnp.uint8)
    )
    return _gens_combine(block, counts, rule)


def halo_step_states_uneven(
    block: jax.Array, rule: GenRule, n: int, height: int, axis: str = AXIS
) -> jax.Array:
    """The balanced-split variant for `height % n != 0` — same seam
    treatment as halo.halo_step_bits_uneven: every shard's physical
    block is ceil(H/n) rows, shard i really owns ceil rows iff
    i < H mod n; the true ring-neighbour row is spliced in after the
    last real row and padding rows are forced dead after the combine
    (a seam birth could otherwise appear in them)."""
    S = block.shape[0]
    idx = lax.axis_index(axis)
    r = height % n
    real = jnp.where(idx < r, S, S - 1)
    down, up = ring_perms(n)
    send_down = lax.dynamic_slice(
        block, (real - 1, jnp.int32(0)), (1, block.shape[1])
    )
    halo_top = lax.ppermute(send_down, axis, down)
    halo_bottom = lax.ppermute(block[:1], axis, up)
    ext = jnp.concatenate([halo_top, block, halo_bottom], axis=0)
    ext = lax.dynamic_update_slice(ext, halo_bottom, (real + 1, jnp.int32(0)))
    ext_alive = (ext == 1).astype(jnp.uint8)
    v = ext_alive[:-2] + ext_alive[1:-1] + ext_alive[2:]
    counts = (
        v + jnp.roll(v, 1, 1) + jnp.roll(v, -1, 1)
        - (block == 1).astype(jnp.uint8)
    )
    new = _gens_combine(block, counts, rule)
    row_ids = lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    return jnp.where(row_ids < real, new, jnp.zeros_like(new))


def _gens_ring_stepper(name, devices, step_n, put, fetch,
                       fetch_diffs=None, one_turn=None,
                       packed_diffs=False, strip=None,
                       sparse_post=None, compact_post=None):
    """Shared Stepper assembly for the sharded gens variants (the
    _ring_stepper analog, plus the family's alive-only count and
    alive_mask). `one_turn` overrides the single-turn step the diff
    scan uses — the packed ring passes its per-turn halo step so the
    watched path never pays deep-block ghost traffic or a pallas
    launch per scanned turn. `strip` (balanced packed split) maps a
    padded (n*Sw, W) word-row array to the canonical (H/32, W) layout
    so step_with_diff masks come out at the true board height."""
    from gol_tpu.parallel.stepper import Stepper, scan_diffs

    @jax.jit
    def step(w):
        return step_n(w, 1)[0]

    @jax.jit
    def step_with_diff(w):
        new, count = step_n(w, 1)
        return new, _changed(w, new), count

    def _changed(old, new):
        if old.dtype == jnp.uint32:  # packed planes (C-1, rows, W)
            x = old[0] ^ new[0]
            for i in range(1, old.shape[0]):
                x = x | (old[i] ^ new[i])
            if strip is not None:
                x = strip(x)
            h = x.shape[0] * WORD
            return bitlife.unpack(x, h) != 0
        return old != new

    @jax.jit
    def count(w):
        if w.dtype == jnp.uint32:
            return bitlife.count_packed(w[0])
        return jnp.sum(w == 1, dtype=jnp.int32)

    def _diff(old, new):
        if old.dtype == jnp.uint32:
            x = old[0] ^ new[0]
            for i in range(1, old.shape[0]):
                x = x | (old[i] ^ new[i])
            return x  # packed (rows, W): 8x smaller on the link
        return old != new

    _snd = scan_diffs(one_turn or (lambda w: step_n(w, 1)[0]), _diff, count)
    # Sparse + compact rows for the packed rings (VERDICT r4 Missing
    # #2; r6 compact chunks): same per-turn scan, diff stripped to the
    # canonical word layout on device, outputs replicated (see
    # packed_halo.replicate_rows / replicate_compact).
    _snd_sparse = None
    _snd_compact = None
    if packed_diffs and one_turn is not None:
        from gol_tpu.parallel.stepper import (
            compact_scan_diffs,
            sparse_scan_diffs,
        )

        def _diff_canonical(old, new):
            x = _diff(old, new)
            return x if strip is None else strip(x)

        _snd_sparse = sparse_scan_diffs(
            one_turn, _diff_canonical, count, post=sparse_post
        )
        _snd_compact = compact_scan_diffs(
            one_turn, _diff_canonical, count, post=compact_post
        )
    _sync = cpu_serializing_sync(devices)

    def alive_mask(levels) -> np.ndarray:
        from gol_tpu.ops.life import ALIVE

        return np.asarray(levels) == ALIVE

    return Stepper(
        name=name,
        shards=len(devices),
        put=put,
        fetch=fetch,
        step=lambda w: _sync(step(w)),
        step_n=lambda w, k: _sync(step_n(w, int(k))),
        step_with_diff=lambda w: _sync(step_with_diff(w)),
        alive_count_async=lambda w: _sync(count(w)),
        alive_mask=alive_mask,
        step_n_with_diffs=lambda w, k: _sync(_snd(w, int(k))),
        fetch_diffs=fetch_diffs,
        packed_diffs=packed_diffs,
        step_n_with_diffs_sparse=(
            None if _snd_sparse is None
            else lambda w, k, cap: _sync(_snd_sparse(w, int(k), int(cap)))
        ),
        step_n_with_diffs_compact=(
            None if _snd_compact is None
            else lambda w, k, cap: _sync(_snd_compact(w, int(k), int(cap)))
        ),
    )


def gens_sharded_stepper(rule: GenRule, devices: list, height: int):
    """Dense sharded Generations: uint8 state strips over a 1-D ring
    mesh, per-turn one-row halos, psum'd alive count. Accepts ANY
    (height, shard-count) pair — non-divisors run the balanced split."""
    n = len(devices)
    if height % n != 0:
        return _gens_sharded_stepper_uneven(rule, devices, height)
    table = partition.table_for("gens_ring")
    mesh = partition.ring_mesh(devices)
    spec = table.resolve("world", ndim=2)
    sharding = partition.named_sharding(mesh, spec)
    from gol_tpu.parallel.halo import DEEP_ROWS

    deep = min(DEEP_ROWS, height // n)

    def deep_block(block):
        """One deep-row STATE ghost exchange, `deep` exact local turns
        of the plain toroidal gens kernel (the halo.sharded_stepper
        deep block with state rows — a ghost cell's multi-turn
        evolution needs its age, which travels with the row; r5
        brought the dense gens ring into the communication-avoiding
        story alongside everything else)."""
        top, bottom = edge_exchange(block, AXIS, depth=deep)
        ext = jnp.concatenate([top, block, bottom], axis=0)
        ext = lax.fori_loop(
            0, deep, lambda _, b: gens.step_states(b, rule), ext
        )
        return ext[deep:-deep]

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n(state, k):
        blocks, rem_t = divmod(max(k, 0), deep) if deep >= 2 else (0, k)

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=spec,
            out_specs=(spec, partition.REPLICATED),
        )
        def _many(block):
            block = lax.fori_loop(
                0, blocks, lambda _, b: deep_block(b), block
            )
            block = lax.fori_loop(
                0, rem_t, lambda _, b: halo_step_states(b, rule, AXIS), block
            )
            count = lax.psum(
                jnp.sum(block == 1, dtype=jnp.int32), AXIS
            )
            return block, count

        return _many(state)

    from gol_tpu.parallel.multihost import spmd_fetch, spmd_put

    def put(levels_world):
        return spmd_put(
            sharding, gens.states_from_levels(levels_world, rule)
        )

    def fetch(arr):
        host = spmd_fetch(arr)
        if host.dtype == np.bool_:
            return host
        return gens.levels_from_states(host, rule)

    return _gens_ring_stepper(
        f"gens-halo-ring-{n}", devices, step_n, put, fetch,
        fetch_diffs=spmd_fetch,
    )


def _gens_sharded_stepper_uneven(rule: GenRule, devices: list, height: int):
    """Balanced-split dense gens ring for non-divisor shard counts —
    device state is (n * ceil(H/n), W) with each shard's real rows at
    the top of its strip (the halo._sharded_stepper_uneven layout)."""
    n = len(devices)
    strip = -(-height // n)
    rem = height % n
    real = [strip if i < rem else strip - 1 for i in range(n)]
    offsets = np.concatenate([[0], np.cumsum(real)])
    table = partition.table_for("gens_ring")
    mesh = partition.ring_mesh(devices)
    spec = table.resolve("world", ndim=2)
    sharding = partition.named_sharding(mesh, spec)

    from gol_tpu.parallel.halo import DEEP_ROWS, balanced_deep_step_n

    deep = min(DEEP_ROWS, strip - 1)  # every ghost from ONE neighbour

    # Deep-halo blocks on the balanced split (r5): ghost STATE rows (a
    # ghost cell's multi-turn evolution needs its age), one d-row
    # exchange per d exact local turns of the plain toroidal gens
    # kernel — the ONE dispatch builder shared with the Life ring.
    step_n = balanced_deep_step_n(
        mesh, spec, n, strip, rem, deep,
        deep_step=lambda b: gens.step_states(b, rule),
        per_turn=lambda b: halo_step_states_uneven(b, rule, n, height),
        count_local=lambda b: jnp.sum(b == 1, dtype=jnp.int32),
    )

    from gol_tpu.parallel.multihost import spmd_fetch, spmd_put

    def put(levels_world):
        host = gens.states_from_levels(levels_world, rule)
        padded = np.zeros((n * strip, host.shape[1]), np.uint8)
        for i in range(n):
            padded[i * strip : i * strip + real[i]] = (
                host[offsets[i] : offsets[i + 1]]
            )
        return spmd_put(sharding, padded)

    def fetch(arr):
        host = spmd_fetch(arr)
        if host.dtype == np.bool_:
            return np.concatenate(
                [host[i * strip : i * strip + real[i]] for i in range(n)]
            )
        host = np.concatenate(
            [host[i * strip : i * strip + real[i]] for i in range(n)]
        )
        return gens.levels_from_states(host, rule)

    def fetch_diffs(d):
        host = spmd_fetch(d)
        return np.concatenate(
            [host[:, i * strip : i * strip + real[i]] for i in range(n)],
            axis=1,
        )

    return _gens_ring_stepper(
        f"gens-halo-ring-uneven-{n}", devices, step_n, put, fetch,
        fetch_diffs,
    )


def packable_gens_sharded(height: int, shards: int) -> bool:
    """Packed gens strips must be whole 32-row words (same geometry as
    packed_halo.packable_sharded)."""
    return (
        shards > 0
        and height % shards == 0
        and (height // shards) % WORD == 0
    )


def halo_step_packed_gens(planes: jax.Array, rule: GenRule,
                          axis: str = AXIS) -> jax.Array:
    """One turn on local packed plane strips (C-1, strip_words, W).

    Only the alive plane feeds the neighbour stencil, so only ITS edge
    word-rows ride the ring; the up/down shifted alive boards take
    their cross-word carries from the halo words exactly as
    packed_halo.halo_step_packed does for Life."""
    alive = planes[0]
    above_last, below_first = edge_exchange(alive, axis)
    carry_up = jnp.concatenate([above_last, alive[:-1]], axis=0)
    up = (alive << jnp.uint32(1)) | (carry_up >> jnp.uint32(WORD - 1))
    carry_down = jnp.concatenate([alive[1:], below_first], axis=0)
    down = (alive >> jnp.uint32(1)) | (carry_down << jnp.uint32(WORD - 1))
    new = bitgens.step_planes(
        tuple(planes[i] for i in range(planes.shape[0])), rule, up, down
    )
    return jnp.stack(new)


def gens_local_block_mode(strip_words: int, width: int, rule: GenRule,
                          on_tpu: bool, force: bool | None = None,
                          max_h: int | None = None) -> tuple:
    """(ghost word-rows h, local stepping mode) for packed gens deep
    blocks — the packed_halo.local_block_mode analog with the gens
    kernels' own VMEM cost models (plane count scales the working
    set), including the 2-D tiled kernel for wide shards (scored with
    the shared thin-strip shape factor)."""
    from gol_tpu.ops import pallas_bitgens
    from gol_tpu.parallel.packed_halo import search_local_block_mode

    if force is False:
        return 1, "xla"
    if width % 128 == 0 and (on_tpu or force):
        ext = strip_words + 2 * _GENS_DEEP_WORDS
        if (ext % 8 == 0
                and (max_h is None or _GENS_DEEP_WORDS <= max_h)
                and pallas_bitgens.fits_pallas_gens(ext * WORD, width, rule)):
            return _GENS_DEEP_WORDS, "whole"

        def plan_1d(e):
            if not pallas_bitgens.fits_pallas_gens_tiled(
                    e * WORD, width, rule):
                return None
            return pallas_bitgens._gens_tile_plan(e, width, rule, None, None)

        def plan_2d(e):
            # Returns None when no width tile fits; its (r, h, wt) is
            # exactly what step_n_packed_gens_pallas_tiled2d_raw runs.
            return pallas_bitgens._gens_tile2d_plan(e, width, rule)

        found = search_local_block_mode(strip_words, plan_1d, plan_2d, max_h)
        if found is not None:
            return found
    return 1, "xla"


#: Ghost slab depth (word-rows per side) for the pallas gens local path.
_GENS_DEEP_WORDS = 4


def packed_gens_sharded_stepper(rule: GenRule, devices: list, height: int,
                                force_local_pallas: bool | None = None):
    """Packed sharded Generations: (C-1, H/32, W) one-hot planes with
    the word-row axis sharded into contiguous strips across `devices`.

    Deep blocks ghost-extend ALL planes (a ghost cell's local evolution
    needs its age), buy 32·h exact local turns per exchange, and run
    the pallas gens kernels inside shard_map on TPU — the packed_halo
    fast-path composition applied per-plane (VERDICT r3 Missing #1).
    `force_local_pallas` mirrors packed_halo (tests exercise the
    composition on CPU meshes in interpreter mode)."""
    n = len(devices)
    if not packable_gens_sharded(height, n):
        raise ValueError(
            f"height {height} not packable into {n} whole-word strips"
        )
    table = partition.table_for("gens_packed_ring")
    mesh = partition.ring_mesh(devices)
    spec = table.resolve("planes", ndim=3)
    sharding = partition.named_sharding(mesh, spec)
    on_tpu = devices[0].platform == "tpu"
    strip_words = (height // n) // WORD

    def deep_block(planes, h: int, mode: str, turns: int):
        from gol_tpu.ops import pallas_bitgens

        assert 1 <= turns <= WORD * h
        # Ghost slabs of every plane: ppermute the (C-1, h, W) edge
        # blocks around the ring (edge_exchange slices axis 0, so the
        # word-row axis is moved to the front first).
        swapped = jnp.swapaxes(planes, 0, 1)  # (rows, C-1, W)
        above_last, below_first = edge_exchange(swapped, AXIS, depth=h)
        ext = jnp.concatenate([above_last, swapped, below_first], axis=0)
        ext = jnp.swapaxes(ext, 0, 1)  # (C-1, rows + 2h, W)
        if mode == "whole":
            ext = pallas_bitgens.step_n_packed_gens_pallas_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        elif mode == "tiled":
            ext = pallas_bitgens.step_n_packed_gens_pallas_tiled_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        elif mode == "tiled2d":
            ext = pallas_bitgens.step_n_packed_gens_pallas_tiled2d_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        else:
            ext = lax.fori_loop(
                0, turns, lambda _, q: bitgens.step_packed_gens(q, rule), ext
            )
        return ext[:, h:-h]

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n(p, k):
        h, mode = gens_local_block_mode(
            strip_words, p.shape[2], rule, on_tpu, force_local_pallas
        )
        big, k2 = divmod(max(k, 0), WORD * h)
        if mode == "xla":
            mid, rem = divmod(k2, WORD)
        else:
            mid, rem = 0, 0

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=spec,
            out_specs=(spec, partition.REPLICATED),
            # pltpu.roll does not propagate the varying-axis tag (see
            # packed_halo.step_n): vma checking is off when a pallas
            # local path is in the program.
            check_vma=mode == "xla",
        )
        def _many(planes):
            planes = lax.fori_loop(
                0, big, lambda _, q: deep_block(q, h, mode, WORD * h), planes
            )
            if mode != "xla" and k2:
                planes = deep_block(planes, h, mode, k2)
            planes = lax.fori_loop(
                0, mid, lambda _, q: deep_block(q, 1, "xla", WORD), planes
            )
            planes = lax.fori_loop(
                0, rem, lambda _, q: halo_step_packed_gens(q, rule), planes
            )
            count = lax.psum(bitlife.count_packed(planes[0]), AXIS)
            return planes, count

        return _many(p)

    from gol_tpu.parallel.multihost import spmd_fetch, spmd_put

    def put(levels_world):
        return spmd_put(
            sharding,
            bitgens.pack_states(
                gens.states_from_levels(levels_world, rule), rule
            ),
        )

    def fetch(arr):
        if getattr(arr, "dtype", None) == jnp.uint32:
            return gens.levels_from_states(
                bitgens.unpack_states(spmd_fetch(arr), height, rule), rule
            )
        return spmd_fetch(arr)

    # Per-turn ring halos for the diff scan (not deep blocks: a depth-h
    # all-plane ghost exchange plus a pallas launch per scanned turn
    # would be pure overhead on a path that needs every intermediate
    # board anyway — the packed_halo._one_turn treatment).
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=spec, out_specs=spec
    )
    def _one_turn(planes):
        return halo_step_packed_gens(planes, rule)

    from gol_tpu.parallel.packed_halo import replicate_compact, replicate_rows

    return _gens_ring_stepper(
        f"gens-packed-halo-ring-{n}", devices, step_n, put, fetch,
        fetch_diffs=spmd_fetch, one_turn=_one_turn, packed_diffs=True,
        sparse_post=replicate_rows(mesh),
        compact_post=replicate_compact(mesh),
    )


def packable_gens_sharded_uneven(height: int, shards: int) -> bool:
    """Word-granular balanced split for the gens planes: every shard
    owns at least one whole 32-row word (packed_halo.
    packable_sharded_uneven, applied to the plane stacks)."""
    from gol_tpu.parallel.packed_halo import packable_sharded_uneven

    return packable_sharded_uneven(height, shards)


def halo_step_packed_gens_balanced(planes: jax.Array, rule: GenRule,
                                   real, axis: str = AXIS) -> jax.Array:
    """One turn on balanced-split packed plane strips: the first `real`
    word-rows of each shard's Sw-row strip are owned, padding below
    stays zero — the packed_halo.halo_step_packed_balanced treatment
    with only the ALIVE plane riding the ring (a gens cell's update
    needs alive-neighbour counts only)."""
    Sw = planes.shape[1]
    alive = planes[0]
    down, up = ring_perms(lax.axis_size(axis))
    send_down = lax.dynamic_slice(
        alive, (real - 1, jnp.int32(0)), (1, alive.shape[1])
    )
    above_last = lax.ppermute(send_down, axis, down)
    below_first = lax.ppermute(alive[:1], axis, up)

    carry_up = jnp.concatenate([above_last, alive[:-1]], axis=0)
    up_b = (alive << jnp.uint32(1)) | (carry_up >> jnp.uint32(WORD - 1))
    carry_down = jnp.concatenate([alive[1:], below_first], axis=0)
    carry_down = lax.dynamic_update_slice(
        carry_down, below_first, (real - 1, jnp.int32(0))
    )
    down_b = (alive >> jnp.uint32(1)) | (carry_down << jnp.uint32(WORD - 1))

    new = jnp.stack(bitgens.step_planes(
        tuple(planes[i] for i in range(planes.shape[0])), rule, up_b, down_b
    ))
    wid = lax.broadcasted_iota(jnp.int32, (1, Sw, 1), 1)
    return jnp.where(wid < real, new, jnp.zeros_like(new))


def packed_gens_sharded_stepper_uneven(rule: GenRule, devices: list,
                                       height: int,
                                       force_local_pallas: bool | None = None):
    """Balanced-split packed Generations ring: (C-1, n*Sw, W) one-hot
    planes, each shard owning the first `real` word-rows of its strip
    (packed_halo.balanced_words), padding zero. Non-divisor shard
    counts keep the SWAR planes, deep halos and pallas local blocks —
    the family parity of VERDICT r4 Missing #1, matching the Life
    ring's packed_sharded_stepper_uneven construction exactly (ghost
    slabs extend ALL planes: a ghost cell's local evolution needs its
    age)."""
    from gol_tpu.parallel.packed_halo import balanced_words

    n = len(devices)
    if not packable_gens_sharded_uneven(height, n):
        raise ValueError(
            f"height {height} not balance-packable over {n} shards"
        )
    total_words = height // WORD
    Sw, real_list = balanced_words(height, n)
    rem_words = total_words % n
    floor_words = total_words // n
    offsets = np.concatenate([[0], np.cumsum(real_list)])
    table = partition.table_for("gens_packed_ring")
    mesh = partition.ring_mesh(devices)
    spec = table.resolve("planes", ndim=3)
    sharding = partition.named_sharding(mesh, spec)
    on_tpu = devices[0].platform == "tpu"

    def _real():
        idx = lax.axis_index(AXIS)
        return jnp.where(idx < rem_words, jnp.int32(Sw), jnp.int32(Sw - 1))

    def deep_block(planes, h: int, mode: str, turns: int, real):
        """One h-word all-plane exchange, `turns` <= 32h exact local
        turns — the Life balanced deep_block per plane (same
        light-cone argument; the spliced below-ghost keeps real rows
        contiguous)."""
        from gol_tpu.ops import pallas_bitgens

        assert 1 <= turns <= WORD * h
        down, up = ring_perms(n)
        swapped = jnp.swapaxes(planes, 0, 1)  # (rows, C-1, W)
        send_down = lax.dynamic_slice(
            swapped,
            (real - h, jnp.int32(0), jnp.int32(0)),
            (h, swapped.shape[1], swapped.shape[2]),
        )
        above = lax.ppermute(send_down, AXIS, down)
        below = lax.ppermute(swapped[:h], AXIS, up)
        ext = jnp.concatenate(
            [above, swapped, jnp.zeros_like(swapped[:h])], axis=0
        )
        ext = lax.dynamic_update_slice(
            ext, below, (h + real, jnp.int32(0), jnp.int32(0))
        )
        ext = jnp.swapaxes(ext, 0, 1)  # (C-1, rows + 2h, W)
        if mode == "whole":
            ext = pallas_bitgens.step_n_packed_gens_pallas_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        elif mode == "tiled":
            ext = pallas_bitgens.step_n_packed_gens_pallas_tiled_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        elif mode == "tiled2d":
            ext = pallas_bitgens.step_n_packed_gens_pallas_tiled2d_raw(
                ext, turns, rule, interpret=not on_tpu
            )
        else:
            ext = lax.fori_loop(
                0, turns, lambda _, q: bitgens.step_packed_gens(q, rule), ext
            )
        out = ext[:, h : h + Sw]
        wid = lax.broadcasted_iota(jnp.int32, (1, Sw, 1), 1)
        return jnp.where(wid < real, out, jnp.zeros_like(out))

    @functools.partial(jax.jit, static_argnames=("k",))
    def step_n(p, k):
        h, mode = gens_local_block_mode(
            Sw, p.shape[2], rule, on_tpu, force_local_pallas,
            max_h=floor_words,
        )
        big, k2 = divmod(max(k, 0), WORD * h)
        if mode == "xla":
            mid, rem_t = divmod(k2, WORD)
        else:
            mid, rem_t = 0, 0

        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=spec,
            out_specs=(spec, partition.REPLICATED),
            check_vma=mode == "xla",
        )
        def _many(planes):
            real = _real()
            planes = lax.fori_loop(
                0, big,
                lambda _, q: deep_block(q, h, mode, WORD * h, real), planes
            )
            if mode != "xla" and k2:
                planes = deep_block(planes, h, mode, k2, real)
            planes = lax.fori_loop(
                0, mid,
                lambda _, q: deep_block(q, 1, "xla", WORD, real), planes
            )
            planes = lax.fori_loop(
                0, rem_t,
                lambda _, q: halo_step_packed_gens_balanced(q, rule, real),
                planes,
            )
            count = lax.psum(bitlife.count_packed(planes[0]), AXIS)
            return planes, count

        return _many(p)

    from gol_tpu.parallel.multihost import spmd_fetch, spmd_put

    from gol_tpu.parallel.packed_halo import strip_padding

    def _strip(d):
        """Padded (..., n*Sw, W) word-rows -> canonical (..., H/32, W)."""
        return strip_padding(d, Sw, real_list)

    def put(levels_world):
        words = bitgens.pack_states(
            gens.states_from_levels(levels_world, rule), rule
        )
        padded = np.zeros((words.shape[0], n * Sw, words.shape[2]),
                          np.uint32)
        for i in range(n):
            padded[:, i * Sw : i * Sw + real_list[i]] = (
                words[:, offsets[i] : offsets[i + 1]]
            )
        return spmd_put(sharding, padded)

    def fetch(arr):
        if getattr(arr, "dtype", None) == jnp.uint32:
            words = strip_padding(spmd_fetch(arr), Sw, real_list)
            return gens.levels_from_states(
                bitgens.unpack_states(words, height, rule), rule
            )
        return spmd_fetch(arr)

    def fetch_diffs(d):
        return strip_padding(spmd_fetch(d), Sw, real_list)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=spec, out_specs=spec
    )
    def _one_turn(planes):
        return halo_step_packed_gens_balanced(planes, rule, _real())

    from gol_tpu.parallel.packed_halo import replicate_compact, replicate_rows

    return _gens_ring_stepper(
        f"gens-packed-halo-ring-uneven-{n}", devices, step_n, put, fetch,
        fetch_diffs=fetch_diffs, one_turn=_one_turn, packed_diffs=True,
        strip=_strip, sparse_post=replicate_rows(mesh),
        compact_post=replicate_compact(mesh),
    )
