"""Partition-rule tables — the ONE place device meshes and shardings
are built.

Every ring/mesh backend used to hand-roll its own ``Mesh(np.asarray(
devices), (AXIS,))`` + ``P(AXIS, None)`` pair, which hard-coded the 1-D
row ring into four modules and made a 2-D scale-out a cross-cutting
edit. This module replaces that plumbing with the declarative pattern
from the pjit lineage (SNIPPETS.md [1]): an ORDERED table of
``regex -> PartitionSpec`` rules, resolved by first match against the
logical NAME of each device array a stepper owns (``world``, ``planes``,
``diffs``, ``sparse_rows``, ``compact_headers``, ``compact_values``,
``stack``, ...). Backends ask the table for their specs; operators
override individual rules from the CLI (``--partition-rule``) without
touching backend code.

Axis vocabulary: a mesh here is always ``Mesh((rows, cols))`` —
``rows`` shards packed word-rows (the inter-host axis on real pods),
``cols`` shards word columns. A 1-D ring is the degenerate ``cols=1``
case; ``ring_mesh`` builds it directly for the legacy backends.

The analysis linter's ``partition-spec`` check enforces the monopoly:
no ``Mesh``/``NamedSharding``/``PartitionSpec`` construction anywhere
else in ``gol_tpu/parallel``.

Layouts: some partition decisions select a KERNEL layout rather than a
sharding (the board is re-chunked inside one device's program). Those
register in ``LAYOUTS`` and are picked by a ``layout=NAME`` entry in
the same override string — ``lane-coupled`` (the PR 4 ``ilp_study``
lane-axis probe, now a library op in ``gol_tpu/ops/lanes.py``) is the
first.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, Iterable, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

#: Mesh axis names — the only two the steppers ever use.
AXIS_ROWS = "rows"
AXIS_COLS = "cols"

#: The replicated spec, importable so backends never spell ``P()``.
REPLICATED = P()


class PartitionError(ValueError):
    """A partition request the table cannot satisfy — an unresolvable
    array name, a rank mismatch, or a malformed mesh/override string."""


def spec(*axes) -> P:
    """Build a PartitionSpec — the constructor backends call instead of
    importing ``P`` themselves (the partition-spec lint pins this)."""
    return P(*axes)


def named_sharding(mesh: Mesh, partition_spec: P) -> NamedSharding:
    """``NamedSharding`` constructor, monopolized here (see lint)."""
    return NamedSharding(mesh, partition_spec)


def parse_mesh(text: str) -> Tuple[int, int]:
    """``"ROWSxCOLS"`` -> ``(rows, cols)``; both positive ints."""
    m = re.fullmatch(r"(\d+)[xX](\d+)", text.strip())
    if not m:
        raise PartitionError(
            f"mesh spec {text!r} is not ROWSxCOLS (e.g. 2x4)"
        )
    rows, cols = int(m.group(1)), int(m.group(2))
    if rows < 1 or cols < 1:
        raise PartitionError(f"mesh {rows}x{cols} has an empty axis")
    return rows, cols


def ring_mesh(devices: Sequence) -> Mesh:
    """The legacy 1-D row ring: ``Mesh((n,), ("rows",))`` over `devices`
    in order (ring neighbours adjacent where the caller's order is)."""
    return Mesh(np.asarray(devices), (AXIS_ROWS,))


def mesh2d(devices: Sequence, rows: int, cols: int) -> Mesh:
    """A ``rows x cols`` device mesh. Row-major assignment keeps each
    mesh row on as few hosts as possible (jax.devices() enumerates
    process-grouped), so the ``cols`` halos ride the fast intra-host
    links and ``rows`` is the inter-host axis."""
    if rows * cols != len(devices):
        raise PartitionError(
            f"mesh {rows}x{cols} needs {rows * cols} devices, "
            f"got {len(devices)}"
        )
    return Mesh(
        np.asarray(devices).reshape(rows, cols), (AXIS_ROWS, AXIS_COLS)
    )


# --- rule tables ---------------------------------------------------------

_AXIS_TOKENS = {
    "rows": AXIS_ROWS,
    "cols": AXIS_COLS,
    "*": None,
    ".": None,
    "none": None,
}


@dataclasses.dataclass(frozen=True)
class Rule:
    """One ordered table entry: arrays whose name matches `pattern`
    (``re.search``) shard as ``P(*axes)``. ``axes=()`` is replicated."""

    pattern: str
    axes: Tuple[Optional[str], ...]

    def __post_init__(self):
        re.compile(self.pattern)  # fail fast on a bad regex
        for a in self.axes:
            if a not in (None, AXIS_ROWS, AXIS_COLS):
                raise PartitionError(
                    f"rule {self.pattern!r}: unknown mesh axis {a!r}"
                )


class RuleTable:
    """Ordered first-match resolver from array names to PartitionSpecs.

    ``resolve(name, ndim=...)`` walks the rules in order and returns the
    FIRST match's spec — order is the override mechanism (operator rules
    are prepended), exactly the semantics of the pjit partition tables
    this mirrors. No match raises PartitionError (an unresolvable array
    is a programming error, never silently replicated); a spec longer
    than the array's rank raises too (a shorter one is fine — trailing
    dims replicate, standard PartitionSpec semantics)."""

    def __init__(self, rules: Iterable[Rule], name: str = "custom",
                 layout: Optional[str] = None):
        self.rules = tuple(rules)
        self.name = name
        #: Kernel layout selected by a ``layout=NAME`` override, if any.
        self.layout = layout

    def resolve(self, array: str, ndim: Optional[int] = None) -> P:
        for rule in self.rules:
            if re.search(rule.pattern, array):
                if ndim is not None and len(rule.axes) > ndim:
                    raise PartitionError(
                        f"table {self.name!r}: rule {rule.pattern!r} "
                        f"spec {rule.axes} has rank {len(rule.axes)} "
                        f"but array {array!r} has rank {ndim}"
                    )
                return P(*rule.axes)
        raise PartitionError(
            f"table {self.name!r} resolves no rule for array "
            f"{array!r} — add a rule or an override"
        )

    def sharding(self, mesh: Mesh, array: str,
                 ndim: Optional[int] = None) -> NamedSharding:
        return NamedSharding(mesh, self.resolve(array, ndim))

    def with_overrides(self, overrides) -> "RuleTable":
        """A new table with operator `overrides` PREPENDED (first match
        wins, so overrides shadow the defaults). `overrides` is either
        an override string (see `parse_overrides`) or parsed rules."""
        if overrides is None:
            return self
        layout = self.layout
        if isinstance(overrides, str):
            rules, layout_over = parse_overrides(overrides)
            layout = layout_over or layout
        else:
            rules = tuple(overrides)
        return RuleTable(rules + self.rules, name=self.name,
                         layout=layout)


def parse_overrides(text: str) -> Tuple[Tuple[Rule, ...], Optional[str]]:
    """Parse a CLI override string into ``(rules, layout)``.

    Grammar: ``entry(;entry)*`` where an entry is ``PATTERN=AXES`` —
    AXES a comma list of ``rows``/``cols``/``*`` (``*`` = replicate
    that dim), or ``-`` for fully replicated — or the special
    ``layout=NAME`` selecting a registered kernel layout:

        --partition-rule 'world=rows,cols;sparse_rows=-'
        --partition-rule 'layout=lane-coupled'
    """
    rules = []
    layout = None
    for entry in text.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise PartitionError(
                f"override {entry!r} is not PATTERN=AXES (or "
                f"layout=NAME)"
            )
        pattern, _, axes_text = entry.partition("=")
        pattern, axes_text = pattern.strip(), axes_text.strip()
        if pattern == "layout":
            get_layout(axes_text)  # unknown layout fails at parse time
            layout = axes_text
            continue
        if axes_text in ("-", ""):
            axes: Tuple[Optional[str], ...] = ()
        else:
            axes_list = []
            for tok in axes_text.split(","):
                tok = tok.strip().lower()
                if tok not in _AXIS_TOKENS:
                    raise PartitionError(
                        f"override {entry!r}: unknown axis {tok!r} "
                        f"(want rows, cols or *)"
                    )
                axes_list.append(_AXIS_TOKENS[tok])
            axes = tuple(axes_list)
        try:
            rules.append(Rule(pattern, axes))
        except re.error as e:
            raise PartitionError(
                f"override {entry!r}: bad pattern ({e})"
            ) from None
    return tuple(rules), layout


#: Shared tail every family ends with: scalar/housekeeping arrays are
#: replicated unless a family (or operator) says otherwise.
_COMMON_TAIL = (
    Rule(r"^(count|mask|sparse_rows|compact_headers|compact_values)$", ()),
    Rule(r"^stack$", ()),
)

#: Default rule tables by backend family. Keys are what the builders
#: pass to `table_for`; the tables cover every device array the family
#: owns, so `resolve` never falls through on in-tree code.
_DEFAULTS: Dict[str, Tuple[Rule, ...]] = {
    # 1-D rings: board rows sharded, everything else as the tail says.
    "dense_ring": (
        Rule(r"^world$", (AXIS_ROWS,)),
        Rule(r"^diffs$", (None, AXIS_ROWS)),
    ) + _COMMON_TAIL,
    "packed_ring": (
        Rule(r"^world$", (AXIS_ROWS, None)),
        Rule(r"^diffs$", (None, AXIS_ROWS, None)),
    ) + _COMMON_TAIL,
    # Dense Generations: uint8 (H, W) state strips — geometrically the
    # dense ring, kept as its own family so operator overrides can
    # target gens without touching Life.
    "gens_ring": (
        Rule(r"^world$", (AXIS_ROWS,)),
        Rule(r"^diffs$", (None, AXIS_ROWS)),
    ) + _COMMON_TAIL,
    # Generations planes: (C-1, H/32, W) — the leading plane axis never
    # shards (aging is a plane rename; splitting it would turn a rename
    # into a collective). The diff stack is a single collapsed bitplane
    # per turn — (k, H/32, W) — so its rule has ring rank, not plane
    # rank.
    "gens_packed_ring": (
        Rule(r"^(world|planes)$", (None, AXIS_ROWS, None)),
        Rule(r"^diffs$", (None, AXIS_ROWS, None)),
    ) + _COMMON_TAIL,
    # 2-D meshes (parallel/mesh2d.py): word-rows x word-columns.
    "packed_mesh2d": (
        Rule(r"^world$", (AXIS_ROWS, AXIS_COLS)),
        Rule(r"^diffs$", (None, AXIS_ROWS, AXIS_COLS)),
    ) + _COMMON_TAIL,
    "gens_mesh2d": (
        Rule(r"^(world|planes)$", (None, AXIS_ROWS, AXIS_COLS)),
        Rule(r"^diffs$", (None, AXIS_ROWS, AXIS_COLS)),
    ) + _COMMON_TAIL,
    # Batch/session stacks and single-device backends: one device, all
    # arrays replicated over the trivial mesh.
    "single": _COMMON_TAIL + (Rule(r"", ()),),
}


def table_for(family: str, overrides: Optional[str] = None) -> RuleTable:
    """The default rule table of a backend `family`, with operator
    `overrides` (CLI string) prepended when given."""
    if family not in _DEFAULTS:
        raise PartitionError(
            f"unknown backend family {family!r} "
            f"(have {sorted(_DEFAULTS)})"
        )
    table = RuleTable(_DEFAULTS[family], name=family)
    return table.with_overrides(overrides)


# --- kernel layouts ------------------------------------------------------

#: name -> factory(rule, **kw) -> ``(packed, n) -> packed`` multi-turn
#: kernel. Selected by a ``layout=NAME`` partition override; consumed
#: by the single-device packed builder (stepper._single_device_packed).
LAYOUTS: Dict[str, Callable] = {}


def register_layout(name: str, factory: Callable) -> None:
    LAYOUTS[name] = factory


def get_layout(name: str) -> Callable:
    try:
        return LAYOUTS[name]
    except KeyError:
        raise PartitionError(
            f"unknown layout {name!r} (have {sorted(LAYOUTS)})"
        ) from None


# The lane-coupled layout (PR 4's ilp_study lane-axis probe, relocated
# to a library op) registers on import — partition is the registry, the
# op module owns the kernel.
from gol_tpu.ops import lanes as _lanes  # noqa: E402

register_layout("lane-coupled", _lanes.make_lane_coupled)
