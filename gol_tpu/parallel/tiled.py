"""Activity-driven tiled stepping — macro-tiles, light-cone skips,
host paging past HBM (ROADMAP open item 3a; docs/PERF.md
"Activity-driven stepping").

Two production truths the dense stepper ignores: real Life boards are
mostly settled space, and a dense dispatch pays for every cell every
turn anyway. This backend tiles the packed universe into fixed
TILE x TILE macro-tiles and steps, per k-turn chunk, ONLY the tiles
whose halo-depth light cone touched a live change:

- **Geometry.** The world stays in the bitlife word layout — uint32
  (H/32, W), 32 vertically-packed cells per word — but lives in HOST
  memory as one numpy array (the paged universe; a 32k x 32k board is
  128 MB of host words and never needs to fit HBM). A macro-tile is a
  (TILE/32, TILE) word sub-array; its ghost-extended block adds `g`
  word-rows above/below and 32*g lanes left/right — exactly the deep-
  halo arithmetic of `parallel/packed_halo.py` (one g-word ghost slab
  buys 32*g exact local turns), applied per tile instead of per ring
  shard.

- **Light-cone skip.** After a k-turn chunk each tile records whether
  its interior changed (chunk-BOUNDARY compare on the fused path;
  any-turn compare on the per-turn diff path, where a mid-chunk
  oscillation must keep emitting flips). A tile is dispatched next
  chunk only when a change landed in its 8-neighbourhood (k <= 32*g
  <= TILE, so the light cone of any change is contained in the
  adjacent tiles) AND its neighbourhood holds any live cell at all
  (an all-zero ghost-extended block provably stays zero under any
  rule without birth-on-0 — which is why B0 rules are rejected, the
  bucket-padding argument of `make_batch_stepper`). Skipping is EXACT,
  not approximate: an unchanged ghost-extended input re-stepped the
  same k turns reproduces the same output, so not re-stepping it
  commits the identical world — the dryrun oracle and the property
  tests gate this bit-for-bit against the dense stepper. A chunk size
  change invalidates the boundary flags (a period-2 island is
  "unchanged" at k=32 but not at k=31), so the first chunk at a new
  (mode, k) re-steps everything with live cells.

- **Per-tile cycle riding.** The PR 10 whole-board cycle machinery
  generalizes tile-wise as memoization: on the fused path each
  dispatched tile's ghost-extended input is digested (16-byte
  blake2b) and mapped to its stepped interior. An oscillating island
  revisits the same ext inputs every period, so after one warm period
  its tiles replay from the cache with ZERO device dispatches — and
  its neighbours, seeing the same boundary cycle, ride too. The cache
  is bounded (global byte budget, FIFO eviction); a digest collision
  is the only approximation (2^-64-grade — and the in-lane oracle
  gate in the bench re-checks the committed world against the dense
  stepper on every capture). The per-turn diff path never consults
  the cache: a replay cannot reconstruct intermediate turns.

- **Host paging.** Only the dispatched batch ever exists on device:
  active ext blocks are gathered host-side, stepped as ONE vmapped
  jit over a pow2-padded slab, and only the interiors come back.
  The slab size is the residency policy — bounded by
  `obs.device.max_resident_tiles` (the same `tile_ext_bytes` x
  working-set arithmetic `fits(resident_tiles=...)` prices, so the
  paging policy and the capacity answer cannot disagree); an active
  set larger than the bound pages through in multiple slabs, all
  gathered from the chunk-start state first so sub-batches stay
  exact. Cold tiles cost no HBM at all.

Recompile discipline: the slab's (capacity, k) are the only shape-
bearing statics. Capacity grows pow2 and never shrinks, k is the
fixed 32*g chunk (plus the run's tail sizes), so a warm pool
dispatches with zero compiles whatever the active set does — pinned
by the cache-census test, the bucket discipline of
`make_batch_stepper` applied tile-wise. Slab padding slots are zero
tiles (zero stays zero; one program for the whole slab).

Event-plane contract: `step_n_with_diffs` emits the same packed
(k, H/32, W) XOR stack as every packed backend (skipped tiles
contribute zero rows — exact, since they did not change), so the
engine's sparse/compact/FBATCH machinery upstream is untouched.
"""

from __future__ import annotations

import functools
import hashlib
import os
import time
from collections import deque
from typing import Optional

import jax
import numpy as np

from gol_tpu import obs
from gol_tpu.models.rules import GenRule, LIFE, Rule, get_rule
from gol_tpu.obs import tracing
# Aliased: the obs-in-jit checker treats every binding of an
# obs-imported name as obs-rooted (see parallel/stepper.py).
from gol_tpu.obs import device as obs_device
from gol_tpu.ops import bitlife
from gol_tpu.ops.bitlife import WORD

#: Device slab bound when the backend reports no memory budget (CPU
#: test meshes): 256 ext tiles of the default 1024 geometry is ~150 MB
#: of transient device arrays — comfortably inside any host the board
#: itself fits on.
DEFAULT_MAX_RESIDENT = 256

#: Ride-cache byte budget (host memory holding memoized tile
#: interiors); GOL_TPU_TILE_RIDE_BUDGET_BYTES overrides, 0 disables.
RIDE_BUDGET_BYTES = 64 * 1024 * 1024


class _TiledMetrics:
    """Registry handles for the activity plane (gol_tpu.obs). The
    per-TILE children ride a TopKGauge — one registry entry whose
    exposition is O(cap) however many tiles a 32k² board holds (the
    PR 12 bounded-cardinality discipline; pinned by a churn test)."""

    def __init__(self):
        self.active = obs.gauge(
            "gol_tpu_engine_active_tiles",
            "Macro-tiles dispatched (stepped or ridden) in the last "
            "activity chunk",
        )
        self.tiles = obs.gauge(
            "gol_tpu_engine_tiles_total",
            "Macro-tiles the current tiled world is split into",
        )
        self.resident = obs.gauge(
            "gol_tpu_engine_resident_tiles",
            "Device tile slots the warm dispatch slab currently holds "
            "(the residency the paging policy priced via fits())",
        )
        self.dispatches = obs.counter(
            "gol_tpu_tiled_dispatches_total",
            "Vmapped tile-slab device dispatches",
        )
        self.tile_steps = obs.counter(
            "gol_tpu_tiled_tile_steps_total",
            "Tile chunks stepped on device",
        )
        self.tile_skips = obs.counter(
            "gol_tpu_tiled_tile_skips_total",
            "Tile chunks skipped as settled (outside every light cone)",
        )
        self.tile_rides = obs.counter(
            "gol_tpu_tiled_tile_rides_total",
            "Tile chunks replayed from the per-tile ride cache "
            "(zero device dispatches)",
        )
        self.paged = {
            d: obs.counter(
                "gol_tpu_tiled_paged_bytes_total",
                "Bytes paged between the host universe and the device "
                "slab (in = ghost-extended uploads, out = interiors "
                "fetched back)",
                {"dir": d},
            ) for d in ("in", "out")
        }
        self.per_tile = obs.registry().topk_gauge(
            "gol_tpu_engine_tile_active_chunks",
            "Consecutive chunks each currently-active tile has been "
            "in the dispatch set (top-K by streak; bounded exposition "
            "— the activity hotspots an operator actually wants named)",
            label="tile", cap=16,
        )


_METRICS = _TiledMetrics()


def tileable(height: int, width: int, tile: int,
             halo_words: int = 1) -> bool:
    """A grid tiles iff the tile divides both axes, is whole words,
    and holds its own light cone (32*g <= TILE keeps any k-turn
    change inside the 8-neighbourhood)."""
    return (
        tile > 0 and halo_words >= 1
        and tile % WORD == 0
        and tile >= WORD * halo_words
        and height % tile == 0
        and width % tile == 0
    )


def _dilate8(m: np.ndarray) -> np.ndarray:
    """Toroidal 8-neighbourhood dilation on the tile grid — the
    light-cone closure (k <= 32*g <= TILE, so one ring suffices)."""
    out = m.copy()
    for dr in (-1, 0, 1):
        for dc in (-1, 0, 1):
            if dr or dc:
                out |= np.roll(np.roll(m, dr, 0), dc, 1)
    return out


class TiledWorld:
    """The handle a tiled Stepper's entries pass around — the engine
    treats it opaquely (commit/fetch/snapshot all work), but it is a
    HOST object: the packed word universe, the per-tile alive counts,
    and the activity flags. Mutated in place by `_advance` (the same
    handle comes back from step_n), which is why the whole-board
    CycleDetector stands down on tiled engines — an anchor reference
    would alias the moving state."""

    __slots__ = ("words", "alive", "tile_alive", "changed", "last_key")

    def __init__(self, words: np.ndarray, tile_alive: np.ndarray):
        self.words = words
        self.tile_alive = tile_alive
        self.alive = int(tile_alive.sum())
        #: Per-tile "interior changed during the last chunk" flags —
        #: boundary-compare on the fused path, any-turn on diffs.
        self.changed = tile_alive > 0
        #: (mode, k) of the last chunk: flags are only meaningful
        #: against the same chunk shape (see module docstring).
        self.last_key: Optional[tuple] = None


class TiledStepper:
    """Host-side implementation behind the `tiled_stepper` Stepper —
    exposed as `Stepper.tiled` so engines and tests can reach the
    activity plane (pool census, ride cache, gather hook)."""

    def __init__(self, rule: "Rule | str" = LIFE, height: int = 512,
                 width: int = 512, tile: int = 1024, *,
                 halo_words: int = 1, device=None,
                 max_resident: Optional[int] = None,
                 ride_budget_bytes: Optional[int] = None):
        rule = get_rule(rule) if isinstance(rule, str) else rule
        if isinstance(rule, GenRule):
            raise ValueError(
                "tiled stepping is two-state only (multi-state planes "
                "would need per-plane ghost slabs — not yet offered)"
            )
        if 0 in rule.birth:
            raise ValueError(
                f"rule {rule} births on 0 neighbours — empty slab "
                "padding and all-zero skipped tiles would seethe, so "
                "B0 rules cannot run the activity-driven path"
            )
        if not tileable(height, width, tile, halo_words):
            raise ValueError(
                f"grid {height}x{width} does not tile into {tile}² "
                f"macro-tiles (tile must divide both axes, be a "
                f"multiple of {WORD}, and hold a {WORD * halo_words}-"
                "cell light cone)"
            )
        self.rule = rule
        self.height, self.width, self.tile = height, width, tile
        self.g = halo_words
        self.tw = tile // WORD                  # word-rows per tile
        self.hw = height // WORD                # word-rows total
        self.gr, self.gc = height // tile, width // tile
        self.ext_h = self.tw + 2 * self.g
        self.ext_w = tile + 2 * WORD * self.g
        #: Exact turns one ghost exchange buys — the per-chunk cap.
        self.max_chunk = WORD * self.g
        self.device = device or jax.devices()[0]
        if max_resident is None:
            max_resident = (obs_device.max_resident_tiles(tile, self.g)
                            or DEFAULT_MAX_RESIDENT)
        self.max_resident = max(1, min(int(max_resident),
                                       self.gr * self.gc))
        #: Current warm slab capacity: starts at 1, grows pow2 on
        #: demand (clamped at max_resident), never shrinks — each
        #: distinct capacity is one compile, so a warm pool re-
        #: dispatches compile-free whatever the active set does.
        self._pool_cap = 1
        if ride_budget_bytes is None:
            env = os.environ.get("GOL_TPU_TILE_RIDE_BUDGET_BYTES")
            try:
                ride_budget_bytes = (int(env) if env
                                     else RIDE_BUDGET_BYTES)
            except ValueError:
                ride_budget_bytes = RIDE_BUDGET_BYTES
        self.ride_budget = max(0, int(ride_budget_bytes))
        #: (tile_index, k, ext digest) -> (interior bytes, changed,
        #: alive) — the per-tile period-riding memo (FIFO-bounded).
        self._ride: dict = {}
        self._ride_order: deque = deque()
        self._ride_bytes = 0
        #: Per-tile consecutive-active streaks feeding the TopKGauge.
        self._streaks: dict = {}

        rule_obj = rule

        @functools.partial(jax.jit, static_argnames=("k",))
        def _step_ext(stack, k):
            # One vmapped program over the whole slab: each ghost-
            # extended block steps k exact local turns with the plain
            # toroidal packed kernel (its wrap garbage lands in the
            # ghost ring the validity shrink already wrote off — the
            # packed_halo deep-block argument, per tile), then only
            # the interiors leave the device.
            out = jax.vmap(
                lambda p: bitlife.step_n_packed_raw(p, k, rule_obj)
            )(stack)
            return out[:, self.g:self.g + self.tw,
                       WORD * self.g:WORD * self.g + self.tile]

        self._step_ext = _step_ext
        _METRICS.tiles.set(self.gr * self.gc)
        _METRICS.resident.set(self._pool_cap)

    # --- Stepper entries -------------------------------------------------

    def put(self, host_world) -> TiledWorld:
        w = np.asarray(host_world, np.uint8)
        if w.shape != (self.height, self.width):
            raise ValueError(
                f"world shape {w.shape} != "
                f"{(self.height, self.width)}"
            )
        words = bitlife.pack_np(w)
        world = TiledWorld(words, self._tile_pops(words))
        _METRICS.tiles.set(self.gr * self.gc)
        return world

    def fetch(self, arr):
        if isinstance(arr, TiledWorld):
            return bitlife.unpack_np(arr.words, self.height)
        return np.asarray(arr)

    def step_n(self, world: TiledWorld, k):
        k = max(int(k), 0)
        while k > 0:
            ks = min(k, self.max_chunk)
            self._advance(world, ks, "fused")
            k -= ks
        return world, world.alive

    def step(self, world: TiledWorld) -> TiledWorld:
        return self.step_n(world, 1)[0]

    def step_n_with_diffs(self, world: TiledWorld, k):
        """Per-turn packed XOR stack, exactly the layout every packed
        backend ships. Turns run one at a time (per-turn exactness is
        the contract — a mid-chunk oscillation must flip), with the
        activity skip still pruning settled tiles; the ride cache
        stands down (a memoized boundary replay cannot reconstruct
        intermediate turns)."""
        k = max(int(k), 0)
        diffs = np.zeros((k, self.hw, self.width), np.uint32)
        for t in range(k):
            self._advance(world, 1, "diffs", collect=diffs[t])
        return world, diffs, world.alive

    def step_with_diff(self, world: TiledWorld):
        _, diffs, count = self.step_n_with_diffs(world, 1)
        mask = bitlife.unpack_np(diffs[0], self.height) != 0
        return world, mask, count

    def alive_count_async(self, world: TiledWorld) -> int:
        return world.alive

    def cache_sizes(self) -> dict:
        """Jit-cache census — the zero-recompile acceptance pin (the
        BatchStepper discipline applied to the tile pool)."""
        fn = self._step_ext
        return {"step_ext": (fn._cache_size()
                             if hasattr(fn, "_cache_size") else None)}

    def activity(self) -> dict:
        """Host-side snapshot of the activity plane (telemetry/bench)."""
        return {
            "tiles": self.gr * self.gc,
            "pool_cap": self._pool_cap,
            "max_resident": self.max_resident,
            "ride_entries": len(self._ride),
            "ride_bytes": self._ride_bytes,
        }

    # --- internals -------------------------------------------------------

    def _tile_pops(self, words: np.ndarray) -> np.ndarray:
        pops = np.bitwise_count(words).astype(np.int64)
        return pops.reshape(self.gr, self.tw, self.gc,
                            self.tile).sum(axis=(1, 3))

    def _gather(self, words: np.ndarray, r: int, c: int) -> np.ndarray:
        """One tile's ghost-extended block, toroidal (corners come from
        the wrap of both index vectors — the full rectangle, so the
        diagonal light cone is exact)."""
        g, tw, T = self.g, self.tw, self.tile
        rows = np.arange(r * tw - g, (r + 1) * tw + g) % self.hw
        cols = np.arange(c * T - WORD * g,
                         (c + 1) * T + WORD * g) % self.width
        return words[np.ix_(rows, cols)]

    def _write(self, world: TiledWorld, r: int, c: int,
               interior: np.ndarray, alive_new: int) -> None:
        tw, T = self.tw, self.tile
        world.words[r * tw:(r + 1) * tw, c * T:(c + 1) * T] = interior
        world.alive += alive_new - int(world.tile_alive[r, c])
        world.tile_alive[r, c] = alive_new

    def _ride_store(self, tidx: int, ks: int, digest: bytes,
                    interior: np.ndarray, changed: bool,
                    alive_new: int) -> None:
        if self.ride_budget <= 0:
            return
        key = (tidx, ks, digest)
        if key in self._ride:
            return
        blob = interior.tobytes()
        while (self._ride_bytes + len(blob) > self.ride_budget
               and self._ride_order):
            old = self._ride_order.popleft()
            gone = self._ride.pop(old, None)
            if gone is not None:
                self._ride_bytes -= len(gone[0])
        if self._ride_bytes + len(blob) > self.ride_budget:
            return
        self._ride[key] = (blob, changed, alive_new)
        self._ride_order.append(key)
        self._ride_bytes += len(blob)

    def _advance(self, world: TiledWorld, ks: int, mode: str,
                 collect: Optional[np.ndarray] = None) -> None:
        """One activity chunk of `ks` turns (ks <= 32*g): select the
        dispatch set, gather EVERY active ext block from the chunk-
        start state (paging sub-batches and ride replays must not see
        each other's writes), replay ride hits, step the rest in
        resident-bounded slabs, commit interiors + flags."""
        key = (mode, ks)
        stale = world.last_key != key
        world.last_key = key
        nonzero = world.tile_alive > 0
        changed_eff = (np.ones_like(world.changed) if stale
                       else world.changed)
        # Dispatch-set selection: inside a change's light cone AND
        # holding (or adjacent to) any live cell — an all-zero ext
        # block stays zero under any non-B0 rule, chunk size be
        # damned, which is what makes a fresh 32k² board with one
        # localized soup cheap from turn 0.
        active = _dilate8(changed_eff) & _dilate8(nonzero)
        idxs = np.flatnonzero(active)
        n_tiles = active.size
        wall0 = time.time()
        t0 = time.perf_counter()
        new_changed = np.zeros_like(world.changed)
        flat_changed = new_changed.reshape(-1)
        use_ride = mode == "fused" and self.ride_budget > 0
        ride_hits = []      # (tidx, r, c, blob, changed, alive)
        pending = []        # (tidx, r, c, ext, digest)
        for tidx in idxs:
            tidx = int(tidx)
            r, c = divmod(tidx, self.gc)
            ext = np.ascontiguousarray(self._gather(world.words, r, c))
            digest = None
            if use_ride:
                digest = hashlib.blake2b(
                    ext.tobytes(), digest_size=16
                ).digest()
                hit = self._ride.get((tidx, ks, digest))
                if hit is not None:
                    ride_hits.append((tidx, r, c) + hit)
                    continue
            pending.append((tidx, r, c, ext, digest))
        # All chunk-start reads are done — writes may begin.
        # Ride replays never coexist with a diff collector: the cache
        # is fused-path-only (use_ride gates on mode), because a
        # boundary replay cannot reconstruct per-turn rows — a future
        # change relaxing that must rebuild the per-turn stack, not
        # emit a whole-chunk XOR as one turn's flips.
        assert collect is None or not ride_hits
        for tidx, r, c, blob, ch, alive_new in ride_hits:
            interior = np.frombuffer(blob, np.uint32).reshape(
                self.tw, self.tile
            )
            self._write(world, r, c, interior, alive_new)
            flat_changed[tidx] = ch
        if pending:
            need = min(len(pending), self.max_resident)
            while self._pool_cap < need:
                self._pool_cap *= 2
            slab = min(self._pool_cap, self.max_resident)
            self._pool_cap = slab
            for start in range(0, len(pending), slab):
                batch = pending[start:start + slab]
                stack = np.zeros((slab, self.ext_h, self.ext_w),
                                 np.uint32)
                for j, (_, _, _, ext, _) in enumerate(batch):
                    stack[j] = ext
                with obs_device.cause("tile-dispatch"):
                    dev = jax.device_put(stack, self.device)
                    out = np.asarray(self._step_ext(dev, ks))
                _METRICS.dispatches.inc()
                _METRICS.paged["in"].inc(stack.nbytes)
                _METRICS.paged["out"].inc(
                    len(batch) * self.tw * self.tile * 4
                )
                tw, T = self.tw, self.tile
                for j, (tidx, r, c, _ext, digest) in enumerate(batch):
                    new_int = out[j]
                    old_int = world.words[r * tw:(r + 1) * tw,
                                          c * T:(c + 1) * T]
                    xor = old_int ^ new_int
                    ch = bool(xor.any())
                    if collect is not None and ch:
                        collect[r * tw:(r + 1) * tw,
                                c * T:(c + 1) * T] = xor
                    alive_new = int(np.bitwise_count(new_int).sum())
                    self._write(world, r, c, new_int, alive_new)
                    flat_changed[tidx] = ch
                    if digest is not None:
                        self._ride_store(tidx, ks, digest, new_int,
                                         ch, alive_new)
        world.changed = new_changed
        # Activity plane: counts this chunk, bounded per-tile streaks.
        dt = time.perf_counter() - t0
        _METRICS.active.set(len(idxs))
        _METRICS.resident.set(self._pool_cap)
        _METRICS.tile_steps.inc(len(pending))
        _METRICS.tile_rides.inc(len(ride_hits))
        _METRICS.tile_skips.inc(n_tiles - len(idxs))
        obs_device.observe_memory()
        live = set()
        for tidx in idxs:
            tidx = int(tidx)
            live.add(tidx)
            streak = self._streaks.get(tidx, 0) + 1
            self._streaks[tidx] = streak
            r, c = divmod(tidx, self.gc)
            _METRICS.per_tile.set_child(f"{r},{c}", streak)
        for tidx in [t for t in self._streaks if t not in live]:
            del self._streaks[tidx]
            r, c = divmod(tidx, self.gc)
            _METRICS.per_tile.remove_child(f"{r},{c}")
        tracing.add_span(
            "engine.tiled_chunk", "engine", wall0, dt,
            {"turns": ks, "active": len(idxs),
             "stepped": len(pending), "rides": len(ride_hits),
             "mode": mode},
        )


def tiled_stepper(rule: "Rule | str" = LIFE, height: int = 512,
                  width: int = 512, tile: int = 1024, *,
                  halo_words: int = 1, device=None,
                  max_resident: Optional[int] = None,
                  ride_budget_bytes: Optional[int] = None):
    """Build the activity-driven tiled backend as a Stepper (the
    `make_stepper(tile=...)` / `--tile` path). Single-device by
    construction: the dispatch SET is the parallelism axis here —
    multi-chip sharding composes at the partition-rule layer
    (ROADMAP open item 4), not inside this backend."""
    from gol_tpu.parallel.stepper import Stepper

    impl = TiledStepper(
        rule, height, width, tile, halo_words=halo_words,
        device=device, max_resident=max_resident,
        ride_budget_bytes=ride_budget_bytes,
    )
    return Stepper(
        name=f"tiled-{tile}",
        shards=1,
        put=impl.put,
        fetch=impl.fetch,
        step=impl.step,
        step_n=impl.step_n,
        step_with_diff=impl.step_with_diff,
        alive_count_async=impl.alive_count_async,
        step_n_with_diffs=impl.step_n_with_diffs,
        fetch_diffs=np.asarray,
        packed_diffs=True,
        tiled=impl,
    )
