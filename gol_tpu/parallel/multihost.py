"""Multi-host initialization — the jax.distributed story.

Topology (SURVEY.md §2 "Distributed / multi-node DP"): the reference
spec's controller ⇄ broker ⇄ workers over net/rpc maps onto two planes:

- **data plane**: every host process runs the SAME jitted step over a
  global mesh spanning all hosts' devices; halo `ppermute`s ride ICI
  within a slice and DCN between slices, inserted by XLA from the same
  `shard_map` program used single-host (parallel/halo.py,
  parallel/packed_halo.py — nothing changes in the kernels).
- **control plane**: the engine server (distributed/server.py) runs on
  the coordinator process only; controllers attach to it over TCP/DCN
  exactly as in the single-host split. IO (PGM read/write) and the
  event stream are coordinator-only; worker processes just execute the
  SPMD program.

This module owns process bootstrap: `initialize()` wraps
`jax.distributed.initialize` (env-var driven, harmless single-process),
`global_ring_mesh()` builds the 1-D row mesh over every device in the
job, and `is_coordinator()` gates the control plane.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from gol_tpu.parallel import partition
from gol_tpu.parallel.stepper import ENTRY_TABLE


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join (or create) a multi-host JAX job.

    Arguments default to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID); with none set this is a no-op so
    the same entry point serves laptops and pods. Call before any other
    jax API touches the backend."""
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        if num_processes is not None or process_id is not None:
            raise ValueError(
                "num_processes/process_id given without a coordinator "
                "address — set coordinator_address or "
                "JAX_COORDINATOR_ADDRESS"
            )
        return  # single-process run
    kwargs: dict = {"coordinator_address": coordinator_address}
    if num_processes is not None or os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = int(
            num_processes
            if num_processes is not None
            else os.environ["JAX_NUM_PROCESSES"]
        )
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = int(
            process_id if process_id is not None else os.environ["JAX_PROCESS_ID"]
        )
    jax.distributed.initialize(**kwargs)


def is_coordinator() -> bool:
    """True on the process that owns IO, events, and the engine server."""
    return jax.process_index() == 0


def is_multiprocess_mesh(devices) -> bool:
    """True when `devices` spans processes, i.e. arrays sharded over them
    are not fully addressable here and transfers must go through the
    multihost paths below."""
    me = jax.process_index()
    return any(d.process_index != me for d in devices)


def spmd_put(sharding, host) -> jax.Array:
    """Host array -> global array under `sharding`, valid whether or not
    the sharding spans processes: every process holds the full host copy
    (the coordinator broadcasts it first — see SPMDDriver.put) and each
    device picks out its own shard."""
    host = np.asarray(host)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )


def spmd_fetch(arr) -> np.ndarray:
    """Global (possibly non-fully-addressable) array -> full host copy
    on every process. All processes must call this together (it is an
    allgather); single-process it is a plain transfer."""
    if jax.process_count() == 1:
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def global_ring_mesh():
    """1-D mesh over every device in the job, ordered so ring neighbours
    are physically adjacent where possible (jax.devices() enumerates
    devices grouped by process, which keeps intra-host hops on ICI)."""
    return partition.ring_mesh(jax.devices())


def device_count() -> int:
    return jax.device_count()


# --- SPMD dispatch mirroring -------------------------------------------------
#
# Under jax.distributed every jitted computation over the global mesh
# must be entered by EVERY process, in the same order, with the same
# static arguments (the SPMD contract). The engine runs on the
# coordinator and makes data/time-dependent dispatch choices (chunk
# sizes, diff-vs-fused paths, snapshot fetches), so the coordinator
# broadcasts a tiny command tuple before each dispatch and worker
# processes replay it against their own reference to the same global
# arrays. This is the worker entry point the reference's spec-level
# "broker ⇄ workers" topology implies (ref: README.md:157-233), done
# the JAX way: the data plane is the jitted program itself; the command
# channel only carries opcodes.
#
# Opcode numbers come straight off the Stepper capability table
# (stepper.ENTRY_TABLE — EntryInfo.opcode is declared STABLE there):
# the table IS the wire protocol, and the mirror below is derived from
# it instead of hand-maintaining per-opcode shims. The only opcodes no
# Stepper entry owns are the world/mask fetch pair (`fetch`
# disambiguates by dtype, so it needs two) and STOP.

_OPS = {e.name: e.opcode for e in ENTRY_TABLE if e.opcode is not None}
_OP_FETCH_WORLD, _OP_FETCH_MASK, _OP_STOP = 5, 6, 7
assert not {_OP_FETCH_WORLD, _OP_FETCH_MASK, _OP_STOP} & set(_OPS.values())


def _bcast(value: np.ndarray) -> np.ndarray:
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.broadcast_one_to_all(
            value, is_source=is_coordinator()
        )
    )


def _bcast_cmd(op: int, arg: int = 0, arg2: int = 0) -> tuple[int, int, int]:
    # int64: `arg` carries fused chunk sizes, and an int32 would wrap a
    # user --chunk >= 2^31 into a different k on the workers than the
    # coordinator runs — a silent ring deadlock. `arg2` carries the
    # sparse cap (a second static argument of the sparse diff scan).
    got = _bcast(np.asarray([op, arg, arg2], np.int64))
    return int(got[0]), int(got[1]), int(got[2])


def round_robin_devices() -> list:
    """Global device list reordered round-robin across processes, so a
    k-device prefix spans as many hosts as possible (jax.devices()'s
    process-grouped order would leave whole hosts idle whenever k fits
    on the first host)."""
    by_proc: dict[int, list] = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, []).append(d)
    groups = [by_proc[p] for p in sorted(by_proc)]
    out = []
    for i in range(max(len(g) for g in groups)):
        for g in groups:
            if i < len(g):
                out.append(g[i])
    return out


def verify_job_config(*fields) -> None:
    """Fail fast when the processes of a multi-host job were launched
    with different run parameters: a mismatch would otherwise build
    divergent SPMD programs whose first collective deadlocks with no
    diagnostic. Every process allgathers every config and every process
    compares ALL of them — a one-way broadcast would let the
    coordinator (whose config trivially equals its own broadcast) sail
    past the check and hang at its first real collective while the
    mismatched worker dies."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    mine = ",".join(str(f) for f in fields).encode()
    buf = np.zeros(256, np.uint8)
    buf[: len(mine)] = np.frombuffer(mine, np.uint8)
    all_cfgs = np.asarray(
        multihost_utils.process_allgather(buf)
    ).reshape(jax.process_count(), -1)
    configs = [bytes(row[row != 0]).decode() for row in all_cfgs]
    if len(set(configs)) > 1:
        raise ValueError(
            f"multi-host config mismatch: {configs} — all processes "
            "must be launched with identical -w/-h/-t/--rule/--backend"
        )


def spmd_stepper(inner):
    """Coordinator-side wrapper: a Stepper whose every dispatch first
    broadcasts (opcode, args) so workers running `spmd_worker_loop` on
    the same inner stepper co-execute it in lockstep. The mirror is
    DERIVED from ENTRY_TABLE — each entry's opcode/args/token
    declaration builds its wrapper, so a new Stepper entry mirrors by
    declaring itself in the table instead of growing another shim here.

    Contract (which the engine satisfies): dispatches are linear in the
    current world — each step consumes the array the previous one
    produced, `fetch` is called on either the current world or the mask
    from the latest `step_with_diff` (told apart by dtype: masks are
    bool)."""
    from gol_tpu.parallel.stepper import Stepper

    # The one legal NON-linear dispatch: after a sparse-overflow, the
    # engine redoes the chunk densely FROM THE SPARSE CALL'S INPUT —
    # through the EXPLICIT `step_n_with_diffs_redo` entry (the engine
    # prefers it whenever a stepper offers one). Workers replay against
    # their own state refs, so the redo is its own opcode telling them
    # to step from the state they saved before the sparse dispatch —
    # replaying it as a plain dense opcode would mix coordinator
    # pre-chunk state with worker post-chunk state and silently diverge
    # the ring. `_sparse_in` tracks the outstanding sparse dispatch's
    # (input, output) pair: the redo asserts it re-steps the exact
    # input, a dense call asserts it continues from the exact output,
    # and anything else raises BEFORE a divergent opcode is broadcast
    # (ADVICE r5 #2 — identity inference replaced by a checked token).
    # Entries are cleared as soon as the sparse dispatch is consumed,
    # which also stops the dict pinning the pre-sparse device buffer.
    # The roles below are keyed by EntryInfo.token ("reset" / "dense" /
    # "sparse" / "redo" — see stepper.EntryInfo).
    _sparse_in = {"in": None, "out": None}

    def _sparse_consumed():
        _sparse_in["in"] = _sparse_in["out"] = None

    def _guard(entry, world):
        """Token-discipline check for `entry`, run BEFORE its opcode
        broadcast so a bad dispatch raises without diverging the ring."""
        if entry.token == "dense" and _sparse_in["in"] is not None:
            if world is _sparse_in["in"]:
                raise RuntimeError(
                    "sparse-overflow redo routed through the plain "
                    "dense entry — the engine must call "
                    "step_n_with_diffs_redo so workers replay from "
                    "their saved pre-sparse state"
                )
            if world is not _sparse_in["out"]:
                raise RuntimeError(
                    "dense diffs dispatch on an unrecognized world "
                    "while a sparse dispatch is outstanding — "
                    "broadcasting it would silently diverge the "
                    "ring (workers would step from post-sparse "
                    "state, the coordinator from something else)"
                )
            _sparse_consumed()
        elif entry.token == "redo":
            if _sparse_in["in"] is None:
                raise RuntimeError(
                    "sparse-overflow redo with no sparse dispatch "
                    "outstanding"
                )
            if world is not _sparse_in["in"]:
                raise RuntimeError(
                    "sparse-overflow redo must re-step the sparse "
                    "dispatch's exact input world"
                )
            _sparse_consumed()
        elif entry.token == "sparse" and _sparse_in["in"] is not None \
                and world is not _sparse_in["out"]:
            if entry.name == "step_n_with_diffs_compact":
                raise RuntimeError(
                    "compact diffs dispatch on an unrecognized world "
                    "while a sparse/compact dispatch is outstanding"
                )
            raise RuntimeError(
                "sparse diffs dispatch on an unrecognized world "
                "while another sparse dispatch is outstanding"
            )

    def _mirror(entry, fn):
        """The generic mirrored entry: guard, broadcast the opcode with
        the entry's int arguments (ALL static arguments ride the
        opcode so every process compiles the identical program — a
        chunk/cap mismatch would be a divergent program and a silent
        deadlock), dispatch, and keep the token record current."""
        def call(world, *args):
            args = tuple(int(a) for a in args)
            _guard(entry, world)
            _bcast_cmd(entry.opcode, *args)
            if entry.token == "reset":
                # A fused dispatch consumes the current world, sparse-
                # produced or not: the outstanding record is spent (a
                # detach switches the engine to this path mid-run;
                # keeping the token would false-flag the first diffs
                # dispatch after reattach).
                _sparse_consumed()
            out = fn(world, *args)
            if entry.token == "sparse":
                _sparse_in["in"], _sparse_in["out"] = world, out[0]
            return out

        return call

    def put(world):
        _bcast_cmd(_OPS["put"])
        host = _bcast(np.asarray(world, np.uint8))
        _sparse_consumed()  # a fresh world abandons any outstanding redo
        return inner.put(host)

    def fetch(arr):
        if getattr(arr, "dtype", None) == np.bool_:
            _bcast_cmd(_OP_FETCH_MASK)
        else:
            _bcast_cmd(_OP_FETCH_WORLD)
        return inner.fetch(arr)

    def fetch_diffs(diffs):
        # The diff stack is told apart from worlds/masks by its own
        # opcode: workers keep the latest stack and gather theirs.
        _bcast_cmd(_OPS["fetch_diffs"])
        return (inner.fetch_diffs or np.asarray)(diffs)

    fields: dict = {}
    for e in ENTRY_TABLE:
        val = getattr(inner, e.name)
        if e.name == "put":
            fields[e.name] = put
        elif e.name == "fetch":
            fields[e.name] = fetch
        elif e.name == "fetch_diffs":
            if inner.step_n_with_diffs is not None:
                fields[e.name] = fetch_diffs
        elif e.name == "step_n_with_diffs_redo":
            # Mirrored whenever the dense entry is: workers replay the
            # redo from their saved pre-sparse state either way, so the
            # coordinator falls back to the dense inner entry when no
            # dedicated redo exists.
            if inner.step_n_with_diffs is not None:
                fields[e.name] = _mirror(e, val or inner.step_n_with_diffs)
        elif e.name == "fetch_compact_values":
            # The compact value buffer is replicated over a mesh that
            # spans processes: a coordinator-only device slice of it
            # would not be addressable, so the mirror materializes the
            # whole buffer with a plain np.asarray (no opcode, no
            # collective — replicated arrays are locally readable on
            # every process) and lets the host take the prefix.
            if inner.step_n_with_diffs_compact is not None:
                fields[e.name] = lambda values, total: np.ascontiguousarray(
                    np.asarray(values)
                ).view(np.uint32)
        elif e.kind == "meta":
            # Host-side metadata (alive_mask level translation, the
            # halo-cost arithmetic — the mirrored ring runs the same
            # block plan, so the inner accounting holds) passes through
            # unmirrored.
            fields[e.name] = val
        elif val is not None:
            fields[e.name] = _mirror(e, val)

    return Stepper(name=f"spmd-{inner.name}", shards=inner.shards, **fields)


def spmd_worker_loop(inner, height: int, width: int) -> None:
    """Run on every non-coordinator process: replay the coordinator's
    dispatch sequence against the same global arrays until _OP_STOP (or
    the coordinator exits, which tears down the distributed client).
    The opcode -> handler map is derived from ENTRY_TABLE's `replay`
    declarations; only the world/mask fetch pair and STOP are wired by
    hand (they are the mirror's own opcodes, not Stepper entries)."""
    st = {"state": None, "mask": None, "diffs": None, "pre": None}

    def _put(arg, arg2):
        host = _bcast(np.zeros((height, width), np.uint8))
        st["state"] = inner.put(host)
        st["pre"] = None

    def _step(arg, arg2):
        st["state"] = inner.step(st["state"])
        st["pre"] = None  # mirror the coordinator: token spent

    def _step_n(arg, arg2):
        st["state"], _ = inner.step_n(st["state"], arg)
        st["pre"] = None

    def _diff(arg, arg2):
        st["state"], st["mask"], _ = inner.step_with_diff(st["state"])

    def _dense(arg, arg2):
        st["state"], st["diffs"], _ = inner.step_n_with_diffs(
            st["state"], arg
        )
        # A dense dispatch means the outstanding sparse chunk (if any)
        # was consumed fine — drop the saved pre-sparse state so it
        # stops pinning a whole board on device.
        st["pre"] = None

    def _sparse(arg, arg2):
        # The sparse rows are replicated; the coordinator reads its
        # local copy, workers just co-execute the scan. The rows go to
        # a throwaway — NOT `diffs` — so a later fetch_diffs opcode
        # still gathers the dense stack the coordinator holds. The
        # pre-sparse state is kept for a possible overflow redo.
        st["pre"] = st["state"]
        st["state"], _rows, _ = inner.step_n_with_diffs_sparse(
            st["state"], arg, arg2
        )

    def _compact(arg, arg2):
        # Compact chunks mirror exactly like sparse rows: headers and
        # the value buffer are replicated (the coordinator reads its
        # local copies, no further opcode), and the pre-dispatch state
        # is kept for a possible overflow redo.
        st["pre"] = st["state"]
        st["state"], _hdr, _vals, _ = inner.step_n_with_diffs_compact(
            st["state"], arg, arg2
        )

    def _redo(arg, arg2):
        # Sparse-overflow redo: the coordinator broadcast the DEDICATED
        # redo opcode (never inferred from identity), so step from the
        # state saved before the sparse dispatch — then drop the save
        # (one redo per sparse, by contract).
        if st["pre"] is None:
            raise RuntimeError(
                "sparse-overflow redo opcode with no sparse "
                "dispatch outstanding — coordinator/worker "
                "dispatch streams have diverged"
            )
        st["state"], st["diffs"], _ = inner.step_n_with_diffs(
            st["pre"], arg
        )
        st["pre"] = None

    def _count(arg, arg2):
        inner.alive_count_async(st["state"])

    def _fetch_diffs(arg, arg2):
        (inner.fetch_diffs or np.asarray)(st["diffs"])

    replays = {
        "put": _put, "step": _step, "step_n": _step_n, "diff": _diff,
        "count": _count, "dense": _dense, "sparse": _sparse,
        "compact": _compact, "redo": _redo, "fetch_diffs": _fetch_diffs,
    }
    handlers = {
        e.opcode: replays[e.replay]
        for e in ENTRY_TABLE
        if e.opcode is not None and e.replay in replays
    }
    handlers[_OP_FETCH_WORLD] = lambda arg, arg2: inner.fetch(st["state"])
    handlers[_OP_FETCH_MASK] = lambda arg, arg2: inner.fetch(st["mask"])
    while True:
        op, arg, arg2 = _bcast_cmd(_OP_STOP)
        if op == _OP_STOP:
            return
        handlers[op](arg, arg2)


def notify_stop() -> None:
    """Coordinator-side: release workers from `spmd_worker_loop`.

    Callers must skip this on an exception path whose error also raised
    on the workers (identical configs fail identically): broadcasting
    to dead peers blocks forever, hiding the diagnostic. Workers of an
    exited coordinator are torn down by the distributed runtime
    instead."""
    if jax.process_count() > 1 and is_coordinator():
        _bcast_cmd(_OP_STOP)
