"""Multi-host initialization — the jax.distributed story.

Topology (SURVEY.md §2 "Distributed / multi-node DP"): the reference
spec's controller ⇄ broker ⇄ workers over net/rpc maps onto two planes:

- **data plane**: every host process runs the SAME jitted step over a
  global mesh spanning all hosts' devices; halo `ppermute`s ride ICI
  within a slice and DCN between slices, inserted by XLA from the same
  `shard_map` program used single-host (parallel/halo.py,
  parallel/packed_halo.py — nothing changes in the kernels).
- **control plane**: the engine server (distributed/server.py) runs on
  the coordinator process only; controllers attach to it over TCP/DCN
  exactly as in the single-host split. IO (PGM read/write) and the
  event stream are coordinator-only; worker processes just execute the
  SPMD program.

This module owns process bootstrap: `initialize()` wraps
`jax.distributed.initialize` (env-var driven, harmless single-process),
`global_ring_mesh()` builds the 1-D row mesh over every device in the
job, and `is_coordinator()` gates the control plane.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from gol_tpu.parallel.halo import AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join (or create) a multi-host JAX job.

    Arguments default to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID); with none set this is a no-op so
    the same entry point serves laptops and pods. Call before any other
    jax API touches the backend."""
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        if num_processes is not None or process_id is not None:
            raise ValueError(
                "num_processes/process_id given without a coordinator "
                "address — set coordinator_address or "
                "JAX_COORDINATOR_ADDRESS"
            )
        return  # single-process run
    kwargs: dict = {"coordinator_address": coordinator_address}
    if num_processes is not None or os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = int(
            num_processes
            if num_processes is not None
            else os.environ["JAX_NUM_PROCESSES"]
        )
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = int(
            process_id if process_id is not None else os.environ["JAX_PROCESS_ID"]
        )
    jax.distributed.initialize(**kwargs)


def is_coordinator() -> bool:
    """True on the process that owns IO, events, and the engine server."""
    return jax.process_index() == 0


def global_ring_mesh() -> Mesh:
    """1-D mesh over every device in the job, ordered so ring neighbours
    are physically adjacent where possible (jax.devices() enumerates
    devices grouped by process, which keeps intra-host hops on ICI)."""
    return Mesh(np.asarray(jax.devices()), (AXIS,))


def device_count() -> int:
    return jax.device_count()
