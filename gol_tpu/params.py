"""Run parameters — the analog of the reference's `gol.Params` quadruple
(ref: gol/gol.go:4-9) plus TPU-native knobs the Go version had no need for.
"""

from __future__ import annotations

import dataclasses

#: Kernel families selectable via Params.backend / make_stepper / --backend.
#: "pallas-packed" is the VMEM-resident packed kernel (whole-board or
#: strip-tiled, ops/pallas_bitlife.py); "auto" prefers it on TPU.
BACKENDS = ("auto", "packed", "dense", "pallas", "pallas-packed")


@dataclasses.dataclass(frozen=True)
class Params:
    """Parameters of the Game of Life run.

    The first four fields are the reference contract (ref: gol/gol.go:4-9,
    flag defaults ref: main.go:17-46). `threads` is reinterpreted the
    TPU-native way: it is the number of *row-strip shards* the grid is
    split into across the device mesh (the reference's dynamic row-farm
    spawned that many goroutines per turn, ref: gol/distributor.go:129).
    Results are shard-count independent, as the reference's tests demand
    thread-count independence (ref: gol_test.go:16-31).
    """

    turns: int = 10000000000
    threads: int = 8
    image_width: int = 512
    image_height: int = 512

    # --- TPU-native knobs (no reference analog) ---
    # Cellular-automaton rule: B/S notation, or an already-resolved
    # models.rules Rule/GenRule (the CLI resolves once and passes the
    # object through, so validation happens at exactly one site).
    # "B3/S23" is Conway Life (ref: gol/distributor.go:325-342).
    rule: "str | object" = "B3/S23"
    # Max turns fused into one on-device lax.fori_loop dispatch when no
    # per-turn event consumer is attached. 1 reproduces the reference's
    # per-turn host cadence exactly. 0 = auto: the engine repeatedly
    # times a short window of warm dispatches and grows to a
    # power-of-two chunk worth ~0.1s at the measured rate (converges in
    # 2-3 stages, each costing one count realization and one recompile)
    # — full kernel throughput on fast hardware, prompt key/pause
    # response everywhere.
    chunk: int = 1
    # Alive-count telemetry cadence in seconds (ref ticker: 2s,
    # gol/distributor.go:285).
    tick_seconds: float = 2.0
    # Kernel family (see BACKENDS — the one authoritative list, shared
    # with parallel/stepper.py and the CLI).
    backend: str = "auto"
    # Directory containing <W>x<H>.pgm inputs (ref: gol/io.go:39) and the
    # output directory (ref: gol/io.go:43).
    image_dir: str = "images"
    out_dir: str = "out"
    # Engine-side periodic auto-checkpoint cadence: snapshot the board to
    # out/<W>x<H>x<turn>.pgm every N completed turns and/or every S
    # seconds (0 disables either). The fault-tolerance story the
    # reference only specified (ref: README.md:261-265): snapshots are
    # crash-atomic complete checkpoints, so a killed engine resumes from
    # the newest one with bounded turn loss (see gol_tpu/checkpoint.py).
    autosave_turns: int = 0
    autosave_seconds: float = 0.0
    # Exact cycle fast-forward (engine/cycles.py): once the board
    # provably revisits an earlier state (full device-side compare, no
    # hashing), the remaining turns collapse modulo the revisit
    # distance — the reference's infeasible 10^10-turn default run
    # completes bit-exactly in seconds once the board goes periodic.
    # Off by default: turn numbers leap when it fires, which per-turn
    # consumers may not expect (the detector only runs headless).
    cycle_detect: bool = False
    # Activity-driven tiled stepping (parallel/tiled.py, --tile):
    # macro-tile side in cells (a positive multiple of 32 dividing
    # both board axes). 0 = off (the dense steppers). With a tile the
    # board is HOST-resident — only tiles a change's light cone
    # touched are dispatched, settled/empty tiles cost nothing, and
    # board size stops being an HBM bound (docs/PERF.md
    # "Activity-driven stepping").
    tile: int = 0
    # 2-D device mesh (parallel/mesh2d.py, --mesh "ROWSxCOLS"): shard
    # the packed board over word-rows AND word-columns with mesh-
    # generic halo exchange. None = the 1-D rings / single device
    # (threads-driven). Exclusive with tile; packed backends only.
    mesh: str | None = None
    # Partition-table overrides (parallel/partition.py,
    # --partition-rule): "PATTERN=AXES;..." entries prepended to the
    # backend family's default rule table, plus "layout=NAME" kernel
    # layout selection. None = family defaults.
    partition_rules: str | None = None

    def __post_init__(self):
        if self.image_width <= 0 or self.image_height <= 0:
            raise ValueError("image dimensions must be positive")
        if self.turns < 0:
            raise ValueError("turns must be >= 0")
        if self.threads < 1:
            raise ValueError("threads must be >= 1")
        if self.chunk < 0:
            raise ValueError("chunk must be >= 1, or 0 for auto")
        if self.tick_seconds <= 0:
            raise ValueError("tick_seconds must be > 0")
        if self.backend not in BACKENDS:
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.autosave_turns < 0:
            raise ValueError("autosave_turns must be >= 0")
        if self.autosave_seconds < 0:
            raise ValueError("autosave_seconds must be >= 0")
        if self.tile < 0 or (self.tile and self.tile % 32):
            raise ValueError(
                "tile must be 0 (off) or a positive multiple of 32"
            )
        if self.mesh is not None:
            # Fail fast on malformed geometry (make_stepper re-parses;
            # this keeps the error at Params construction, where the
            # CLI can attribute it to the flag).
            from gol_tpu.parallel import partition

            try:
                partition.parse_mesh(self.mesh)
            except partition.PartitionError as e:
                raise ValueError(str(e)) from None
        if self.partition_rules is not None:
            from gol_tpu.parallel import partition

            try:
                partition.parse_overrides(self.partition_rules)
            except partition.PartitionError as e:
                raise ValueError(str(e)) from None

    @property
    def input_name(self) -> str:
        """Input image stem, `<W>x<H>` (ref: gol/distributor.go:39)."""
        return f"{self.image_width}x{self.image_height}"

    def output_name(self, turn: int | None = None) -> str:
        """Output image stem `<W>x<H>x<turns>` (ref: gol/distributor.go:181,
        's'-snapshot variant ref: gol/distributor.go:230)."""
        t = self.turns if turn is None else turn
        return f"{self.image_width}x{self.image_height}x{t}"
