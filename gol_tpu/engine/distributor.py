"""The distributor — turn scheduler, event emitter, controller services.

Re-design of the reference's `distributor` (ref: gol/distributor.go:30-209)
for a device-resident world:

- The world lives on TPU as an immutable device array; the *single*
  engine thread owns the ref. Each committed (turn, world) pair is
  published atomically, so the ticker reads a consistent snapshot
  without the reference's shared mutex (whose turn counter was read
  racily, ref: gol/distributor.go:94,118 vs :291-294).
- Per-turn CellFlipped diffs are computed on device as `old != new`
  masks and shipped to the host in one bulk transfer
  (ref: gol/distributor.go:212-220 did a host-side W×H scan emitting
  one event per cell). When no consumer needs diffs, the engine runs
  `chunk` turns per dispatch inside `lax.fori_loop` without touching
  the host at all — the events-off fast path.
- Control (ticker, keyboard verbs s/q/p/k, pause) interleaves with the
  turn loop between dispatches, replacing the reference's four extra
  goroutines + mutex (ref: gol/distributor.go:86-89,223-302).

Verb semantics (ref README.md:177-183 and gol/distributor.go:223-280):
  's'  snapshot current world to out/<W>x<H>x<turn>.pgm (async write)
  'q'  snapshot, then stop gracefully — unlike the reference's
       os.Exit(0) (ref: gol/distributor.go:261) the event stream is
       closed properly; in distributed mode this detaches the
       controller and the engine keeps evolving (see distributed/)
  'p'  pause/resume with StateChange events
  'k'  snapshot + full shutdown (the verb the reference forwards but
       never handles, ref: sdl/loop.go:25-26, README.md:183)
"""

from __future__ import annotations

import atexit
import contextlib
import queue
import threading
import time
import weakref
from typing import Iterator, Optional

import numpy as np

from gol_tpu import obs
from gol_tpu.engine.cycles import CycleDetector
from gol_tpu.obs import accounting, device, flight, tracing
from gol_tpu.events import (
    AliveCellsCount,
    BoardSync,
    CellFlipped,
    Event,
    FinalTurnComplete,
    FlipBatch,
    FlipChunk,
    ImageOutputComplete,
    State,
    StateChange,
    TurnComplete,
)
from gol_tpu.io.service import IOService
from gol_tpu.ops import life
from gol_tpu.params import Params
from gol_tpu.parallel import make_stepper
from gol_tpu.utils.cell import cells_from_mask, xy_from_mask
from gol_tpu.analysis.concurrency import lockcheck


def _charge_legacy(seconds: float, turns: int) -> None:
    """Accounting plane: the singleton engine serves the anonymous
    `legacy` tier — every dispatch is one tenant's spend, priced off
    the published engine.step cost (gol_tpu.obs.accounting)."""
    m = accounting.meter()
    if m is not None:
        m.charge(accounting.LEGACY, dispatch_seconds=seconds,
                 flops=m.price_flops("engine.step") * turns,
                 turns=turns)


def _is_gen_rule(rule) -> bool:
    from gol_tpu.models.rules import GenRule

    return isinstance(rule, GenRule)


_CLOSE = object()

#: Turns per dispatch on the device-accumulated diff path: the engine
#: steps up to this many turns in ONE program that stacks the per-turn
#: flip masks on device, then ships the whole stack in one transfer —
#: per-turn dispatch+fetch round trips (each ~100 ms through a tunnel
#: link) collapse into one per chunk (VERDICT r3 Weak #1). Bounded so
#: verbs/pause stay responsive within a chunk's wall time.
DIFF_CHUNK = 256
#: Device-memory ceiling for one diff stack (bytes); caps the chunk on
#: big boards (a dense 16384² bool stack is 256 MB at k=1).
DIFF_STACK_BUDGET = 128 * 1024 * 1024
#: Sparse diff encoding (packed backends): a row is a changed-word
#: bitmap (total_words/8 bytes) plus `cap` values (4 bytes each), vs
#: total_words*4 for the full mask — capping values at total_words//2
#: guarantees >=~1.9x less on the link even when the cap is saturated,
#: and a quiet board approaches the bitmap floor (32x).
DIFF_SPARSE_MIN_CAP = 64

# Engines whose thread may still be running. The engine thread is
# non-daemon (see Engine.start), so an abandoned infinite run would pin
# interpreter shutdown forever. Plain atexit fires too late — CPython
# joins non-daemon threads BEFORE atexit callbacks — so this uses
# threading._register_atexit, which runs at the start of
# threading._shutdown (the hook concurrent.futures relies on for the
# same problem).
_live_engines: "weakref.WeakSet" = weakref.WeakSet()


def register_live_engine(engine) -> None:
    """Enroll any device-owning loop (Engine, sessions.SessionEngine)
    in the interpreter-exit stop discipline above. Duck-typed: the
    object needs `stop()` and `join(timeout)`; weakly held, so
    enrollment never extends a loop's lifetime."""
    _live_engines.add(engine)


def _stop_live_engines() -> None:
    for engine in list(_live_engines):
        engine.stop()
        engine.join(timeout=30)


try:
    threading._register_atexit(_stop_live_engines)
except AttributeError:  # private API; fall back for exotic interpreters
    atexit.register(_stop_live_engines)


class _EngineMetrics:
    """Handles into the process-global registry, resolved once at
    import (metric lookups are dict + lock; the hot loop must only pay
    the `inc`). All instrumentation is per DISPATCH — never per turn,
    never per cell, never inside a jitted program (the `obs-in-jit`
    linter check pins that). Engines share these series: the registry
    is process-global, like the reference's single event stream."""

    def __init__(self):
        kinds = ("chunk", "diff", "diffs", "ride")
        self.dispatches = {
            k: obs.counter(
                "gol_tpu_engine_dispatches_total",
                "Engine device dispatches by path kind",
                {"kind": k},
            ) for k in kinds
        }
        self.turns = {
            k: obs.counter(
                "gol_tpu_engine_turns_total",
                "Turns committed by path kind",
                {"kind": k},
            ) for k in kinds
        }
        self.dispatch_seconds = {
            k: obs.histogram(
                "gol_tpu_engine_dispatch_seconds",
                "Wall seconds per dispatch (diff paths: measured; "
                "fused chunks: only when a Timeline realizes them)",
                {"kind": k},
            ) for k in kinds
        }
        self.host_seconds = obs.histogram(
            "gol_tpu_engine_host_seconds",
            "Host-side decode + event fan-out seconds per diff chunk",
        )
        self.committed_turn = obs.gauge(
            "gol_tpu_engine_committed_turn", "Last committed turn"
        )
        self.alive_cells = obs.gauge(
            "gol_tpu_engine_alive_cells",
            "Alive cells at the last realised (turn, count) pair",
        )
        self.effective_chunk = obs.gauge(
            "gol_tpu_engine_effective_chunk",
            "Turns per fused dispatch actually in use",
        )
        self.queue_depth = obs.gauge(
            "gol_tpu_engine_event_queue_depth",
            "Approximate unconsumed events in the engine's queue",
        )
        self.sparse_chunks = obs.counter(
            "gol_tpu_engine_sparse_chunks_total",
            "Diff chunks shipped with the sparse encoding",
        )
        self.sparse_redos = obs.counter(
            "gol_tpu_engine_sparse_redos_total",
            "Sparse chunks redone densely after a cap overflow",
        )
        self.compact_chunks = obs.counter(
            "gol_tpu_engine_compact_chunks_total",
            "Diff chunks shipped with the variable-length compact "
            "encoding",
        )
        self.compact_bytes = obs.counter(
            "gol_tpu_engine_compact_bytes_total",
            "Host-link bytes fetched for compact diff chunks "
            "(headers + used value prefix)",
        )
        self.compact_ratio = obs.gauge(
            "gol_tpu_engine_compact_ratio",
            "Last compact chunk's fetched bytes over the dense packed "
            "stack's bytes for the same turns",
        )
        self.compact_redos = obs.counter(
            "gol_tpu_engine_compact_redos_total",
            "Compact chunks redone densely after a value-buffer "
            "overflow",
        )
        self.throttle_stalls = obs.counter(
            "gol_tpu_engine_throttle_stalls_total",
            "Times the engine entered the event-backpressure wait",
        )
        self.skipped_turns = obs.counter(
            "gol_tpu_engine_skipped_turns_total",
            "Turns collapsed by the exact cycle fast-forward",
        )


_METRICS = _EngineMetrics()


class EventQueue:
    """The events channel (ref: `events chan gol.Event`, main.go:53).

    Unbounded; iteration ends when the producer closes it (the analog of
    `close(events)`, ref: gol/distributor.go:206)."""

    def __init__(self):
        self._q: queue.Queue = queue.Queue()
        self._closed = threading.Event()
        self._consumed = 0

    def put(self, ev: Event) -> None:
        self._q.put(ev)

    def put_many(self, evs) -> None:
        """Enqueue a whole batch under ONE lock acquisition. At the
        batched-wire rates (10⁵ events/s) the per-put lock handshake
        of queue.Queue is itself a measured ~5µs/event ceiling; this
        reaches into the documented queue internals (mutex / queue /
        not_empty — the attributes queue.Queue subclassing is built
        on) to amortize it."""
        q = self._q
        with q.mutex:
            q.queue.extend(evs)
            q.unfinished_tasks += len(evs)
            q.not_empty.notify_all()

    def get_batch(self, max_n: int = 4096,
                  timeout: Optional[float] = None) -> Optional[list]:
        """Up to `max_n` queued events in one call: blocks for the
        first like `get`, then drains whatever else is already queued
        under one lock — the consumer-side twin of `put_many`. None
        once the queue is closed and drained; `queue.Empty` on a
        timeout with nothing queued (exactly `get`'s contract)."""
        first = self.get(timeout=timeout)
        if first is None:
            return None
        out = [first]
        q = self._q
        with q.mutex:
            while len(out) < max_n and q.queue:
                item = q.queue[0]
                if item is _CLOSE:
                    break  # keep the sentinel for the next get
                q.queue.popleft()
                out.append(item)
        self._consumed += len(out) - 1
        return out

    def qsize(self) -> int:
        """Approximate backlog — the producer-side backpressure signal
        (the reference throttles via its 1000-slot channel buffer,
        ref: main.go:53; here the queue is unbounded so a blocked put
        can never wedge shutdown, and the engine throttles itself on
        this instead — see Engine._throttle_events)."""
        return self._q.qsize()

    @property
    def consumed(self) -> int:
        """Monotone count of events handed to consumers — lets the
        producer tell a *lagging* consumer (worth waiting for) from a
        run with no consumer at all (must not be waited on)."""
        return self._consumed

    def close(self) -> None:
        self._closed.set()
        self._q.put(_CLOSE)

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def get(self, timeout: Optional[float] = None):
        """Next event; None once the queue is closed and drained.
        A `timeout` with no event raises `queue.Empty` (timeout is
        distinguishable from closure on purpose — None always means
        the stream ended)."""
        item = self._q.get(timeout=timeout)
        if item is _CLOSE:
            self._q.put(_CLOSE)  # keep the sentinel for other consumers
            return None
        self._consumed += 1
        return item

    def __iter__(self) -> Iterator[Event]:
        while True:
            item = self._q.get()
            if item is _CLOSE:
                self._q.put(_CLOSE)
                return
            self._consumed += 1
            yield item


class Engine:
    """One run of the automaton: load → turn loop → final output."""

    def __init__(
        self,
        params: Params,
        events: Optional[EventQueue] = None,
        keypresses: Optional[queue.Queue] = None,
        *,
        emit_flips: bool = True,
        emit_turns: Optional[bool] = None,
        emit_flip_batches: bool = False,
        emit_flip_chunks: bool = False,
        initial_world: Optional[np.ndarray] = None,
        start_turn: int = 0,
        io_service: Optional[IOService] = None,
        stepper=None,
        timeline=None,
        cycle_check_seconds: float = 2.0,
    ):
        self.p = params
        self.events = events if events is not None else EventQueue()
        self.keypresses = keypresses
        self.emit_flips = emit_flips
        # Per-turn flips as ONE FlipBatch ndarray event instead of N
        # CellFlipped objects (events.FlipBatch): opt-in for consumers
        # that apply flips vectorized (the engine server, the local
        # visualiser); the per-cell stream stays the reference contract.
        self.emit_flip_batches = emit_flip_batches
        # Whole diff chunks as ONE FlipChunk event (events.FlipChunk)
        # instead of k (FlipBatch, TurnComplete) pairs — the emit path
        # behind the batched wire (ROADMAP item 1): at 10⁵ turns/s the
        # per-turn Python event objects are the measured bottleneck.
        # Live-togglable (the server re-derives it from attached
        # peers); engages only where the chunk layout is exact — see
        # _chunk_mode.
        self.emit_flip_chunks = emit_flip_chunks
        #: Turns per diff dispatch a batching watcher asked for (the
        #: negotiated hello "batch" max-k, via the server). 0 = none;
        #: a positive hint RAISES the DIFF_CHUNK budget so a watcher
        #: that consumes k-turn frames isn't capped at the interactive
        #: chunk size (ISSUE 10's chunk-pinning fix).
        self.batch_turns_hint = 0
        # Per-turn TurnComplete in the fused-chunk path is pure overhead
        # when nothing consumes per-turn granularity — a 10^10-turn
        # headless run would spend its host time on queue puts (VERDICT
        # r1 Weak #2). Default: follow emit_flips (the "someone watches
        # per-turn" signal; the diff path always emits per turn anyway).
        # Pass emit_turns=True to get the reference's per-turn events
        # without flips.
        self.emit_turns = emit_flips if emit_turns is None else emit_turns
        self._initial_world = initial_world
        # Resuming from a checkpoint: the world is `initial_world` as of
        # `start_turn` completed turns (PGM snapshots are complete state,
        # turn number in the filename — SURVEY.md §5 checkpoint/resume).
        if start_turn < 0 or start_turn > params.turns:
            raise ValueError("start_turn must be in [0, turns]")
        self.start_turn = start_turn
        # Stepper before IOService: make_stepper validates (and can
        # raise on) the backend/grid combination, and the IO service
        # spawns a live thread that a failed construction would leak.
        self.stepper = stepper or make_stepper(
            threads=params.threads,
            height=params.image_height,
            width=params.image_width,
            rule=params.rule,
            backend=params.backend,
            tile=params.tile,
            mesh=params.mesh,
            partition_rules=params.partition_rules,
        )
        self.io = io_service or IOService(params.image_dir, params.out_dir)
        self._own_io = io_service is None
        # Atomically published (completed_turns, device_world, device_count);
        # the mutex-free replacement for ref: gol/distributor.go:34-36.
        # ONLY the engine thread dispatches device work or realises device
        # values: the device programs contain collectives, and a second
        # thread blocking on the device wedges the collective rendezvous
        # when host cores are scarce. Other threads (ticker, controllers)
        # ask for counts via _count_req and the engine services them
        # between dispatches.
        self._committed = (0, None, None)
        self._paused = False
        self._stop_reason: Optional[str] = None
        self._ticker_stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._req_lock = lockcheck.make_lock("Engine._req_lock")
        # Pending cross-thread requests, each ("count"|"world", event, box).
        self._requests: list = []
        # Last (turn, count) pair actually realised together — the
        # always-consistent fallback for timed-out requests.
        self._last_pair = (0, 0)
        self._finished = threading.Event()
        #: Optional utils.trace.Timeline recording one span per dispatch.
        #: Profiling realizes each chunk's count so spans measure true
        #: device time, at the cost of serializing the dispatch pipeline
        #: (the usual observer tax; ref analog: wrapping the whole run in
        #: runtime/trace, trace_test.go:19-27).
        self.timeline = timeline
        #: Exception that killed the engine thread, if any.
        self.error: Optional[BaseException] = None
        #: The dispatch chunk actually in use (auto-calibration updates
        #: it when Params.chunk == 0).
        self.effective_chunk = max(params.chunk, 1) if params.chunk else 64
        self._throttle_disabled = False
        # Exact cycle fast-forward (Params.cycle_detect): detector state
        # plus the turn count it skipped (surfaced for tests/telemetry).
        self._cycles = (
            CycleDetector(cycle_check_seconds) if params.cycle_detect
            else None
        )
        self.skipped_turns = 0
        # Gray-level Generations visualisation (r5, VERDICT r4 Missing
        # #3): with a multi-state rule and batches on, flip batches
        # carry per-cell levels. A CHANGED gens cell's new state is a
        # pure LUT of its old one — dead that changed was born (1);
        # alive that changed starts dying; dying always ages — so the
        # existing changed-cell masks alone determine every level once
        # the host tracks a state grid alongside.
        self._gens_levels: Optional[dict] = None
        rule_obj = params.rule
        if isinstance(rule_obj, str):
            from gol_tpu.models.rules import get_rule

            rule_obj = get_rule(rule_obj)
        if emit_flip_batches and _is_gen_rule(rule_obj):
            from gol_tpu.ops.generations import levels as _levels_lut

            c = rule_obj.states
            self._gens_levels = {
                "rule": rule_obj,
                "next": np.array(
                    [1] + [(s + 1) % c for s in range(1, c)], np.uint8
                ),
                "lut": _levels_lut(rule_obj),
                "states": None,
            }
        # Sparse diff encoding state: None = ship full masks; an int =
        # the changed-word cap for the next sparse chunk (see
        # _run_diff_chunk). Starts off; the first plain chunk's observed
        # activity enables it.
        self._sparse_cap: Optional[int] = None
        # Cycle-RIDING state for the watched chunk path (the watched
        # twin of the fused path's cycle fast-forward, r10): once the
        # detector proves the board periodic and a probe pins a small
        # period m, chunks of whole periods are SYNTHESIZED from the
        # recorded period's diff rows — no device dispatch, turn
        # numbers stay dense, every emitted flip bit-exact by the
        # device-side equality proof. Only with Params.cycle_detect,
        # only in chunk mode (see _maybe_create_ride).
        self._ride: Optional[dict] = None
        self._ride_probe_due = False
        self._ride_cycles = (
            CycleDetector(min(cycle_check_seconds, 1.0))
            if params.cycle_detect else None
        )
        if self.stepper.offers("tiled"):
            # Activity-driven tiled backend: the whole-board cycle
            # machinery stands down. Per-tile period-riding (the ride
            # cache inside parallel/tiled.py) subsumes it at finer
            # grain, and the tiled world handle is mutated in place —
            # a CycleDetector anchor would alias the moving state and
            # "prove" a period instantly.
            self._cycles = None
            self._ride_cycles = None
        # In-flight chunk of the pipelined diff path (see
        # _diff_pipeline_step); engine thread only.
        self._pending_diffs: Optional[dict] = None
        # True while a diff chunk's per-turn rows are being emitted:
        # sync requests are deferred then (see _diff_consume).
        self._emitting = False
        self._last_diff_span_end = 0.0

    # --- public api ---

    def start(self) -> "Engine":
        """Run asynchronously (the analog of `go gol.Run(...)`).

        The thread is non-daemon on purpose: interpreter shutdown while
        the engine is mid-dispatch tears down XLA under a live C++ frame
        (pthread forced-unwind → terminate). The engine always ends —
        `run()`'s finally closes the stream — so waiting for it at exit
        is bounded once the run finishes or is told to stop."""
        self._thread = threading.Thread(target=self.run, name="gol-engine")
        register_live_engine(self)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Programmatic graceful stop: end the turn loop at the next
        dispatch boundary without the 'q'/'k' snapshot side effects. The
        stream still closes with StateChange{Quitting}."""
        self._stop_reason = self._stop_reason or "stop"
        self._paused = False

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def completed_turns(self) -> int:
        return self._committed[0]

    def health(self) -> dict:
        """Liveness snapshot for /healthz (gol_tpu.obs.http): host-side
        committed state only — safe from any thread, never touches the
        device, cheap enough for a probe to hammer."""
        turn, count = self._last_pair
        return {
            "status": "error" if self.error is not None else "ok",
            "completed_turns": self.completed_turns,
            "target_turns": self.p.turns,
            "alive_cells": count,
            "alive_cells_turn": turn,
            "paused": self._paused,
            "finished": self._finished.is_set(),
            "effective_chunk": self.effective_chunk,
            "error": repr(self.error) if self.error is not None else None,
        }

    def alive_count_now(self, timeout: float = 5.0) -> tuple[int, int]:
        """(completed_turns, alive_count) of the last committed world —
        safe from any thread: posts a request the engine thread services
        between dispatches (no foreign-thread device access). On timeout
        (engine paused/finished/dead) returns the last consistent pair."""
        if not self._finished.is_set():
            ev = threading.Event()
            box: dict = {}
            with self._req_lock:
                self._requests.append(("count", ev, box))
            if ev.wait(timeout):
                return box["turn"], box["count"]
        return self._last_pair

    def request_board_sync(self, enable_flips: bool = False, token: int = 0) -> None:
        """Ask the engine thread to publish a BoardSync event at the next
        dispatch boundary, optionally turning on per-turn CellFlipped
        diffs *at that same boundary* — so a subscriber that applies the
        sync then the flips never misses or double-applies a turn.
        `token` is echoed on the BoardSync so the consumer can match the
        sync to the subscriber that asked for it."""
        with self._req_lock:
            self._requests.append(
                ("sync", None, {"enable_flips": enable_flips, "token": token})
            )

    # --- engine thread ---

    def run(self) -> None:
        try:
            self._run()
        except BaseException as e:
            # The reference log.Fatal's on any engine error
            # (ref: gol/distributor.go:50-52, util/check.go); here the
            # stream closes cleanly and the error is kept for callers.
            self.error = e
            # The black-box moment: dump the recent dispatch history
            # crash-atomically BEFORE teardown (gol_tpu.obs.flight —
            # a no-op without a configured dump directory), so the
            # post-mortem pins the turn the engine died at even when
            # the traceback only lands in a log.
            flight.note("engine.fatal", error=repr(e))
            with contextlib.suppress(Exception):
                flight.dump("engine-exception")
        finally:
            self._ticker_stop.set()
            self._finished.set()
            self._service_requests()  # release any waiting requester
            self.events.close()  # idempotent; unblocks all consumers
            if self._own_io:
                self.io.stop()

    def _run(self) -> None:
        p = self.p
        # World load (ref: gol/distributor.go:38-69): from the IO service
        # unless the caller injected a board (tests, resume-from-snapshot).
        if self._initial_world is not None:
            host_world = np.asarray(self._initial_world, np.uint8)
        else:
            host_world = self.io.read(p.input_name)
        if host_world.shape != (p.image_height, p.image_width):
            raise ValueError(
                f"image {p.input_name} has shape {host_world.shape}, "
                f"params say {(p.image_height, p.image_width)}"
            )
        # Seed the consistent (turn, count) pair from the host board and
        # start the ticker BEFORE any device work: stepper.put and the
        # first compiles can take tens of seconds on a cold TPU, and the
        # first AliveCellsCount must still land within the reference's
        # 5s watchdog (ref: count_test.go:30-38) — served from this pair
        # until the first dispatch commits.
        self._last_pair = (self.start_turn, int(np.count_nonzero(host_world)))
        _METRICS.alive_cells.set(self._last_pair[1])
        ticker = threading.Thread(target=self._ticker, name="gol-ticker", daemon=True)
        ticker.start()

        world = self.stepper.put(host_world)

        self._seed_gens_states(host_world)

        # Initial CellFlipped burst for every live cell
        # (ref: gol/distributor.go:72-80).
        if self.emit_flips:
            if self._gens_levels is not None:
                # Level mode: the opening batch SETS every nonzero
                # cell's gray level (dying cells included), the
                # multi-state analog of the alive burst.
                nz = host_world != 0
                self.events.put(FlipBatch(
                    self.start_turn, xy_from_mask(nz), levels=host_world[nz]
                ))
            else:
                mask = self._alive_mask(host_world)
                if self.emit_flip_batches:
                    self.events.put(
                        FlipBatch(self.start_turn, xy_from_mask(mask))
                    )
                else:
                    for cell in cells_from_mask(mask):
                        self.events.put(CellFlipped(self.start_turn, cell))

        self._commit(self.start_turn, world, self.stepper.alive_count_async(world))

        # Auto-checkpoint cadence trackers (Params.autosave_*): the
        # engine-side fault story the reference spec asks for
        # (ref: README.md:261-265) — periodic crash-atomic snapshots so
        # a killed engine loses at most one cadence interval.
        self._autosave_turn = self.start_turn
        self._autosave_time = time.monotonic()

        # Auto-chunk calibration (Params.chunk == 0): starting at 64
        # turns/dispatch, repeatedly (a) realize once after the first
        # dispatch at the current size so compiles stay out of the
        # measurement, (b) time a short window of warm dispatches,
        # (c) grow to a power-of-two chunk worth ~0.1s at the measured
        # rate. Stops when the chunk stops growing — each stage's rate
        # includes per-dispatch overhead, so 2-3 stages converge (64 →
        # dispatch-bound rate → kernel-bound rate). A fixed chunk of 64
        # caps a tunnel-attached TPU at ~1% of the kernel rate; the cap
        # of 2^18 keeps pause/key/snapshot response well under a second
        # on any hardware.
        chunk = 64 if p.chunk == 0 else p.chunk
        cal = {"phase": "warm", "since": self.start_turn} if p.chunk == 0 else None
        self.effective_chunk = chunk

        turn = self.start_turn
        while turn < p.turns and self._stop_reason is None:
            self._service_requests()
            self._poll_keys(turn)
            if self._stop_reason is not None:
                break
            if self.emit_flips:
                if self.stepper.offers("step_n_with_diffs"):
                    if self._ride is not None:
                        new_turn = self._ride_step(turn)
                        if new_turn != turn:
                            turn = new_turn
                            world = self._committed[1]
                            continue
                        # Ride abandoned without emitting: fall
                        # through to a real dispatch (the committed
                        # world is the true phase-0 board, so real
                        # stepping resumes seamlessly).
                    elif self._ride_probe_due:
                        self._ride_probe_due = False
                        # The in-flight pipelined chunk (if any) is
                        # superseded: its turns re-emit from the ride
                        # (or from a fresh dispatch off the same
                        # committed world if the probe fails) — its
                        # events were never emitted, so nothing is
                        # lost or doubled.
                        self._pending_diffs = None
                        self._maybe_create_ride(turn)
                        if self._ride is not None:
                            continue
                    if not self.stepper.offers("fetch_diffs"):
                        # Single-device: overlap each chunk's transfer
                        # with the previous chunk's fan-out.
                        turn = self._diff_pipeline_step(turn)
                    else:
                        # Sharded/mirrored: the gather is a collective
                        # that must stay in dispatch order.
                        turn = self._run_diff_chunk(turn)
                    world = self._committed[1]
                    continue
                tick = time.perf_counter()
                new_world, mask, count = self.stepper.step_with_diff(world)
                turn += 1
                host_mask = self.stepper.fetch(mask)
                # fetch(mask) synced the dispatch: the span measures
                # device time, not the host event fan-out below.
                elapsed = time.perf_counter() - tick
                _METRICS.dispatches["diff"].inc()
                _METRICS.turns["diff"].inc()
                _METRICS.dispatch_seconds["diff"].observe(elapsed)
                _charge_legacy(elapsed, 1)
                tracing.add_span("engine.dispatch", "engine",
                                 time.time() - elapsed, elapsed,
                                 {"kind": "diff", "turn": turn,
                                  "turns": 1})
                if self.timeline:
                    self.timeline.record(turn, 1, elapsed, "diff")
                self._emit_turn_flips(turn, host_mask)
                world = new_world
                self._commit(turn, world, count)
                self.events.put(TurnComplete(turn))
                self._throttle_events()
                self._maybe_autosave(turn, world)
            else:
                # A controller detach mid-pipeline switches paths: the
                # in-flight diff chunk's turns must land first. Any
                # cycle ride is dropped — fused stepping moves the
                # board off the ride's phase anchor.
                self._ride = None
                turn = self._flush_pending_diffs(turn)
                world = self._committed[1]
                if cal is not None and not self.emit_turns:
                    # Calibration only advances on an undisturbed engine:
                    # an attached controller caps dispatches (and taxes
                    # the loop), so locking a chunk from that rate would
                    # strand the post-detach run undersized.
                    if cal["phase"] == "warm":
                        if turn > cal["since"]:
                            int(self._committed[2])  # compile+1st chunk done
                            cal = {"phase": "measure", "since": turn,
                                   "t0": time.monotonic(),
                                   "deadline": time.monotonic() + 0.3,
                                   "retries": cal.get("retries", 0)}
                    elif time.monotonic() >= cal["deadline"]:
                        int(self._committed[2])  # drain the queued chain
                        elapsed = time.monotonic() - cal["t0"]
                        retries = cal.get("retries", 0)
                        if elapsed > 1.5:
                            # Disturbed window (pause, verbs, host stall):
                            # that rate is not the engine's — re-measure
                            # instead of locking it in.
                            cal = {"phase": "warm", "since": turn}
                        else:
                            rate = (turn - cal["since"]) / max(elapsed, 1e-6)
                            target = max(64, min(1 << 18, int(rate * 0.1)))
                            new_chunk = 1 << target.bit_length() - 1
                            if new_chunk > chunk:
                                chunk = new_chunk
                                self.effective_chunk = chunk
                                cal = {"phase": "warm", "since": turn}
                            elif chunk == 64 and retries < 3:
                                # Converging at the warm-up size usually
                                # means a polluted first window (sub-1.5s
                                # stall, brief attach) — a 10^10-turn run
                                # must not be locked to ~1% of kernel
                                # rate by it. Re-measure a few times; a
                                # genuinely slow platform converges after
                                # the retries.
                                cal = {"phase": "warm", "since": turn,
                                       "retries": retries + 1}
                            else:
                                cal = None  # converged
                # Snapshot the consumer state for THIS dispatch: an
                # attached controller caps the dispatch size (bounded
                # TurnComplete bursts, sub-second verb response), and a
                # controller attaching mid-dispatch must not trigger a
                # full-chunk burst of pre-sync events it would discard.
                emit_now = self.emit_turns
                k = min(chunk, 1024 if emit_now else chunk, p.turns - turn)
                if p.autosave_turns > 0:
                    # Honor the checkpoint cadence exactly: a dispatch
                    # never overshoots the next autosave boundary, so a
                    # kill loses at most one cadence interval even with
                    # a user-set chunk far larger than the cadence.
                    k = max(1, min(
                        k, self._autosave_turn + p.autosave_turns - turn
                    ))
                tick = time.perf_counter()
                with device.cause("fused-chunk"):
                    world, count = self.stepper.step_n(world, k)
                # Fused chunks report only the enqueue leg of the
                # device split: nothing is fetched per chunk, so the
                # sync boundary does not exist here (realizing one
                # would BE the observer tax this path avoids).
                device.observe_split(
                    enqueue_s=time.perf_counter() - tick
                )
                _METRICS.dispatches["chunk"].inc()
                _METRICS.turns["chunk"].inc(k)
                _METRICS.effective_chunk.set(self.effective_chunk)
                # Fused chunks charge the enqueue leg (nothing is
                # realized per chunk — same boundary as the device
                # split above).
                _charge_legacy(time.perf_counter() - tick, k)
                if self.timeline:
                    int(count)  # realize: spans measure true device time
                    elapsed = time.perf_counter() - tick
                    # The fused path's histogram is fed only under a
                    # Timeline: without the realization above, a wall
                    # timing would measure the async enqueue, not the
                    # dispatch (the observer tax stays opt-in).
                    _METRICS.dispatch_seconds["chunk"].observe(elapsed)
                    tracing.add_span("engine.dispatch", "engine",
                                     time.time() - elapsed, elapsed,
                                     {"kind": "chunk", "turn": turn + k,
                                      "turns": k})
                    self.timeline.record(turn + k, k, elapsed, "chunk")
                else:
                    # Un-realized dispatch: an instant mark keeps the
                    # fused cadence on the timeline without the
                    # realizing observer tax a measured span would
                    # force.
                    tracing.event("engine.dispatch", "engine",
                                  kind="chunk", turn=turn + k, turns=k)
                first = turn + 1
                turn += k
                self._commit(turn, world, count)
                if emit_now:
                    for t in range(first, turn + 1):
                        self.events.put(TurnComplete(t))
                    self._throttle_events()
                self._maybe_autosave(turn, world)
                # Gate on the LIVE consumer flag, not this dispatch's
                # snapshot: a controller attaching mid-dispatch must not
                # watch the turn counter leap right after its BoardSync.
                if self._cycles is not None and not self.emit_turns:
                    m = self._cycles.observe(turn, world)
                    if m:
                        # The board provably equals its state m turns
                        # ago: the remaining turns collapse modulo m,
                        # bit-exactly. One jump per run; the final
                        # `remaining % m` turns step normally.
                        skip = (p.turns - turn) // m * m
                        if skip:
                            turn += skip
                            self.skipped_turns = skip
                            _METRICS.skipped_turns.inc(skip)
                            self._commit(turn, world, count)
                            self._autosave_turn = turn
                            # One jump per run: done observing.
                            self._cycles = None
                        # skip == 0: the revisit distance exceeds the
                        # remaining turns — keep observing; a tighter
                        # revisit (anchor distances shrink as the walk
                        # re-anchors) could still collapse the tail.

        # An in-flight diff chunk's turns are computed and its events
        # owed — quit verbs land at chunk boundaries, exactly as on the
        # unpipelined path.
        turn = self._flush_pending_diffs(turn)
        world = self._committed[1] if self._committed[1] is not None else world

        self._ticker_stop.set()
        self._last_pair = (turn, int(self._committed[2]))
        _METRICS.alive_cells.set(self._last_pair[1])
        # Serve any sync request that arrived during the last dispatch
        # BEFORE the tail events are queued, so a just-attached
        # subscriber gets its BoardSync and then the final events instead
        # of a silently empty stream.
        self._service_requests()

        if self._stop_reason == "stop":
            # Programmatic stop (Engine.stop / atexit): no snapshot, just
            # a clean close of the stream.
            self.events.put(StateChange(turn, State.QUITTING))
            self.events.close()
            return

        if self._stop_reason in ("q", "k"):
            # Snapshot-and-stop (ref: gol/distributor.go:244-261, but with
            # a clean close instead of os.Exit(0)).
            self._write_snapshot(turn, world, wait=True)
            self.io.check_idle()
            self.events.put(StateChange(turn, State.QUITTING))
            self.events.close()
            return

        # Normal completion (ref: gol/distributor.go:180-206).
        self._write_snapshot(turn, world, wait=True)
        self.events.put(
            FinalTurnComplete(
                turn,
                cells_from_mask(self._alive_mask(self.stepper.fetch(world))),
            )
        )
        self.io.check_idle()
        self.events.put(StateChange(turn, State.QUITTING))
        self.events.close()

    def _run_diff_chunk(self, turn: int) -> int:
        """One dispatch of the device-accumulated diff path: step up to
        DIFF_CHUNK turns in one program, ship the stacked per-turn flip
        masks in one transfer, expand them host-side with NumPy and emit
        the *identical* per-turn CellFlipped/TurnComplete stream the
        one-turn path produced (ref contract: gol/distributor.go:212-220
        via sdl_test.go:57-74). Returns the new completed-turn count.

        Steady-state watched runs on a slow host link ride the
        device-compacted encodings when the stepper offers them: once a
        plain chunk shows the board changes few enough words per turn,
        subsequent chunks ship COMPACT chunks — per-turn [count,
        bitmap] headers plus ONE shared stream-compacted value buffer,
        fetched only up to the summed count, so the link pays for
        actual activity (r6) — or, on steppers without the compact
        entry, fixed-width sparse [count, bitmap, values] rows. Both
        adapt the cap to observed activity; an overflow (activity
        burst past the cap/buffer) is detected from the counts and the
        chunk is redone densely — the stream is bit-identical on every
        path."""
        return self._diff_consume(turn, self._diff_dispatch(turn))

    def _diff_pipeline_step(self, turn: int) -> int:
        """One iteration of the PIPELINED diff path (single-device
        steppers): dispatch the next chunk — its device compute and its
        host transfer (started eagerly with copy_to_host_async) overlap
        the expansion and event fan-out of the chunk dispatched on the
        previous iteration — then consume that previous chunk. The
        event stream and its ordering are untouched: chunk N's events
        are always emitted, and N committed, before any of chunk N+1's.
        `_run`'s epilogue consumes a still-pending chunk when the loop
        exits (quit verbs land at chunk boundaries, as before)."""
        ahead = self._pending_diffs["k"] if self._pending_diffs else 0
        nxt = turn + ahead
        new_pending = (
            self._diff_dispatch(nxt) if nxt < self.p.turns else None
        )
        if self._pending_diffs is not None:
            turn = self._diff_consume(turn, self._pending_diffs)
        self._pending_diffs = new_pending
        return turn

    def _flush_pending_diffs(self, turn: int) -> int:
        """Consume the in-flight diff chunk, if any (loop exit)."""
        if self._pending_diffs is not None:
            turn = self._diff_consume(turn, self._pending_diffs)
            self._pending_diffs = None
        return turn

    #: Longest exact period the watched cycle ride will record. The
    #: ride holds one period of S-sparse diff rows host-side plus the
    #: phase-0 device world — a 1024-turn period of a settled 512²
    #: board is ~6 MB of host arrays.
    RIDE_MAX_PERIOD = 1024

    def _maybe_create_ride(self, turn: int) -> None:
        """Pin an exact small period and record one period's diffs —
        the watched twin of the fused cycle fast-forward. The anchor
        walk (CycleDetector) already PROVED the committed world equals
        an earlier state; this probe walks forward in doubling
        segments recording the per-turn diff rows, and finds the
        smallest period HOST-side: world(t) == world(0) exactly when
        the XOR of the recorded diffs S[1..t] cancels, so a prefix-XOR
        scan over the walked stack detects ANY period ≤ the walk —
        period-3/6/15 oscillators included, not just divisors of the
        walk length. Failure (no period within RIDE_MAX_PERIOD — e.g.
        a torus-circumnavigating glider) costs one bounded walk, backs
        the next probe off exponentially (a genuinely aperiodic board
        must not pay a recurring probe tax), and the run continues
        stepping for real."""
        from gol_tpu.parallel.stepper import sparse_chunk_from_dense

        world, count = self._committed[1], self._committed[2]
        if (world is None or not self._chunk_mode()
                or self._ride_cycles is None):
            return
        fetch = self.stepper.fetch_diffs or np.asarray
        segs = []
        cur = world
        q = 0
        step = 2
        m = None
        while q + step <= self.RIDE_MAX_PERIOD:
            with device.cause("cycle-probe"):
                nxt, diffs, _c = self.stepper.step_n_with_diffs(
                    cur, step
                )
            segs.append(
                np.asarray(fetch(diffs)).reshape(step, -1)
                .view(np.uint32)
            )
            cur = nxt
            q += step
            stack = np.concatenate(segs, axis=0)
            prefix = np.bitwise_xor.accumulate(stack, axis=0)
            zero = np.flatnonzero(~prefix.any(axis=1))
            if zero.size:
                m = int(zero[0]) + 1
                break
            step = q  # segments 2, 2, 4, 8, ... — cumulative doubling
        if m is None:
            # Exponential probe backoff: double the detector's compare
            # interval each failure, so an anchor-revisiting board
            # with only LARGE periods stops paying the walk.
            self._ride_cycles.interval = min(
                self._ride_cycles.interval * 2, 300.0
            )
            tracing.event("engine.ride_probe_failed", "engine",
                          turn=turn, walked=q)
            return
        counts, bitmaps, words = sparse_chunk_from_dense(stack[:m])
        # Whole periods per synthesized chunk, tiled up to the chunk
        # budget — Params.chunk still paces the ride (an operator's
        # explicit pacing bounds burst size for per-turn peers), with
        # one period as the floor; frames to batch peers split further
        # by their negotiated max-k.
        budget = self._diff_chunk_budget()
        if self.p.chunk > 0:
            budget = min(budget, self.p.chunk)
        r = max(1, budget // m)
        self._ride = {
            "m": m, "r": r, "world": world, "count": count,
            "wpp": int(counts.sum()),
            "counts": np.tile(counts, r),
            "bitmaps": np.tile(bitmaps, (r, 1)),
            "words": np.tile(words, r),
        }
        tracing.event("engine.ride_start", "engine", turn=turn,
                      period=m, tile=r)
        flight.note("engine.ride_start", turn=turn, period=m)

    def _ride_step(self, turn: int) -> int:
        """Emit one synthesized chunk of whole proven periods: no
        device dispatch, the committed world stays the REAL phase-0
        board (every chunk is a whole number of periods, so syncs,
        snapshots and the final output all read a world that exactly
        matches the committed turn). Returns `turn` unchanged when the
        ride must stand down (consumer mix changed, or fewer than one
        period of turns remains — the tail steps for real)."""
        ride = self._ride
        m = ride["m"]
        r = min(ride["r"], (self.p.turns - turn) // m)
        if r <= 0 or not self._chunk_mode():
            self._ride = None
            return turn
        k = r * m
        self.events.put(FlipChunk(
            turn + k, first_turn=turn + 1,
            counts=ride["counts"][:k],
            bitmaps=ride["bitmaps"][:k],
            words=ride["words"][:ride["wpp"] * r],
        ))
        _METRICS.dispatches["ride"].inc()
        _METRICS.turns["ride"].inc(k)
        tracing.event("engine.dispatch", "engine", kind="ride",
                      turn=turn + k, turns=k)
        self._commit(turn + k, ride["world"], ride["count"])
        turn += k
        self._throttle_events()
        self._maybe_autosave(turn, ride["world"])
        return turn

    def _diff_dispatch(self, turn: int) -> dict:
        """Dispatch one diff chunk starting after `turn` completed
        turns and start its host transfer; no host-blocking work.

        Dispatch runs one chunk AHEAD of consume on the pipelined path,
        so the mutable knobs it reads are a chunk stale: the sparse cap
        may already be doomed (an activity burst costs up to two dense
        redos instead of one — the price of the one-chunk lag), and the
        autosave anchor is projected forward to the boundary the
        in-flight chunk will land on (consume caps chunks exactly at
        cadence boundaries, so anchors only ever sit on them; the
        projection can never overshoot, only avoid spurious 1-turn
        chunks)."""
        p = self.p
        pipelined = self._pending_diffs is not None or (
            not self.stepper.offers("fetch_diffs")
        )
        k = min(self._diff_chunk_budget(), self._diff_chunk_cap(pipelined),
                p.turns - turn)
        if p.chunk > 0:
            k = min(k, p.chunk)
        if p.autosave_turns > 0:
            # Never overshoot the autosave boundary (same contract as
            # the fused path), against the projected anchor (see above).
            anchor = self._autosave_turn
            if turn > anchor:
                anchor += (turn - anchor) // p.autosave_turns * p.autosave_turns
            k = min(k, max(1, anchor + p.autosave_turns - turn))
        world = self._committed[1] if turn == self._committed[0] else None
        if world is None:
            # Pipelined dispatch continues from the not-yet-committed
            # world of the in-flight chunk.
            world = self._pending_diffs["new_world"]
        pending = {"k": k, "world_before": world, "sparse_cap": None,
                   "compact_cap": None, "tick": time.perf_counter()}
        with device.cause("diff-chunk"):
            if (self._sparse_cap is not None
                    and self.stepper.offers("step_n_with_diffs_compact")):
                # Variable-length compact chunk (r6): the fetch pays for
                # headers + actual activity, not the cap — preferred over
                # fixed-width sparse rows whenever the stepper offers it.
                total_cap = self._compact_total_cap(k)
                pending["compact_cap"] = total_cap
                _METRICS.compact_chunks.inc()
                new_world, buf, values, count = (
                    self.stepper.step_n_with_diffs_compact(world, k,
                                                           total_cap)
                )
                # The value buffer is NOT eagerly copied: the used prefix
                # is unknowable until the headers land, and an async copy
                # of the whole (total_cap,) slab would ship the very
                # per-turn value reservation this encoding exists to
                # avoid. Only the header stack overlaps the fan-out.
                pending["values"] = values
            elif self._sparse_cap is not None:
                pending["sparse_cap"] = self._sparse_cap
                _METRICS.sparse_chunks.inc()
                new_world, buf, count = (
                    self.stepper.step_n_with_diffs_sparse(
                        world, k, self._sparse_cap
                    )
                )
            else:
                new_world, buf, count = self.stepper.step_n_with_diffs(
                    world, k
                )
        start_copy = getattr(buf, "copy_to_host_async", None)
        if start_copy is not None:  # overlap the transfer (jax Arrays)
            start_copy()
        # Host overhead to get the dispatch in flight — the `enqueue`
        # leg of the device-vs-host split (gol_tpu.obs.device).
        pending["enqueue_s"] = time.perf_counter() - pending["tick"]
        pending.update(new_world=new_world, buf=buf, count=count)
        return pending

    def _diff_chunk_budget(self) -> int:
        """Turns per diff dispatch before the memory cap: DIFF_CHUNK,
        RAISED to a batching watcher's negotiated max-k
        (batch_turns_hint) — the chunk is what one wire frame carries,
        so pinning it at the interactive size would cap the batched
        path's amortization at DIFF_CHUNK regardless of negotiation.
        Verb latency within a chunk's wall time stays bounded: a
        batching watcher explicitly traded per-turn interactivity for
        throughput."""
        return max(DIFF_CHUNK, self.batch_turns_hint)

    def _compact_total_cap(self, k: int) -> int:
        """Value-buffer size for the next compact chunk: the maximum
        turns a chunk can carry times the per-turn activity cap the
        sparse adaptation maintains (2x headroom over the observed
        peak). Sized from the CHUNK BUDGET rather than this dispatch's
        `k` — not to save compiles (`k` is itself a static argument of
        the scan, so a clipped chunk recompiles either way) but so a
        tail/autosave-clipped chunk inherits the full chunk's absolute
        burst headroom instead of a proportionally tinier buffer that
        a single active turn could overflow. `max(..., k)` is only a
        guard; k never exceeds the budget by construction."""
        budget = min(self._diff_chunk_budget(), self._diff_chunk_cap(False))
        if self.p.chunk > 0:
            budget = min(budget, self.p.chunk)
        return max(budget, k) * self._sparse_cap

    def _diff_chunk_cap(self, pipelined: bool) -> int:
        """Max diff-chunk turns the device stack budget allows, from the
        actual per-turn diff representation: packed word-row diffs are
        H*W/8 bytes (uint32 words of 32 cells), dense bool masks H*W —
        sizing packed backends as dense would clamp big boards to
        chunks 8x under budget (ADVICE r4). Pipelined dispatch keeps
        two stacks alive, so it halves the budget."""
        p = self.p
        budget = DIFF_STACK_BUDGET // (2 if pipelined else 1)
        per_turn = p.image_height * p.image_width
        if self.stepper.offers("packed_diffs"):
            per_turn //= 8
        return max(1, budget // max(per_turn, 1))

    def _chunk_mode(self) -> bool:
        """True when diff chunks should emit as ONE FlipChunk event:
        a chunk consumer asked for it AND the per-turn diff layout is
        the packed vertical-word grid the wire's changed-word
        convention mirrors exactly (wire.grid_words). Everything else
        — gens level streams, dense-mask backends, ragged heights —
        keeps the per-turn path (consumers negotiate batches as an
        optimization, never a requirement)."""
        return (self.emit_flip_chunks and self.emit_flip_batches
                and self._gens_levels is None
                and self.stepper.offers("packed_diffs")
                and self.p.image_height % 32 == 0)

    def _diff_consume(self, turn: int, pending: dict) -> int:
        """Materialize one dispatched diff chunk: decode (with the
        sparse-overflow dense fallback), commit, emit, autosave.

        The chunk's final turn/world are committed BEFORE its per-turn
        events are emitted, so `completed_turns` (and the ticker's
        alive sample) can run up to the chunk size ahead of what event
        consumers have drained — the same observability skew as the
        fused path; the event stream content itself is identical to
        the per-turn path (pinned by tests/test_diffs.py).

        With a chunk consumer attached (_chunk_mode) the whole decoded
        stack emits as ONE FlipChunk event in the device's S-sparse
        layout — no dense row scatter, no per-turn event objects: the
        two costs that capped the watched path at ~300 turns/s."""
        k = pending["k"]
        new_world, count = pending["new_world"], pending["count"]
        chunk_mode = self._chunk_mode()
        rows = None
        chunk = None
        encoded = (pending["sparse_cap"] is not None
                   or pending["compact_cap"] is not None)
        if pending["compact_cap"] is not None:
            got = (self._chunk_from_compact(pending) if chunk_mode
                   else self._decode_compact(pending))
            if got is None:  # Σ counts burst past the value buffer
                _METRICS.compact_redos.inc()
                tracing.event("engine.compact_redo", "engine",
                              turn=turn + k,
                              total_cap=pending["compact_cap"])
                flight.note("engine.compact_redo", turn=turn + k)
        elif pending["sparse_cap"] is not None:
            got = (self._chunk_from_sparse(pending) if chunk_mode
                   else self._decode_sparse(pending))
            if got is None:  # truncated: the board burst past the cap
                _METRICS.sparse_redos.inc()
                tracing.event("engine.sparse_redo", "engine",
                              turn=turn + k, cap=pending["sparse_cap"])
                flight.note("engine.sparse_redo", turn=turn + k)
        else:
            got = None
        if chunk_mode:
            chunk = got
        else:
            rows = got
        if encoded and rows is None and chunk is None:
            self._sparse_cap = None
            # The EXPLICIT redo entry when the stepper has one
            # (mirrored steppers broadcast a dedicated opcode so
            # workers re-step from their saved pre-dispatch state —
            # never inferred from object identity); plain steppers
            # redo through the ordinary dense scan.
            redo = (self.stepper.step_n_with_diffs_redo
                    or self.stepper.step_n_with_diffs)
            with device.cause("diff-redo"):
                new_world, diffs, count = redo(pending["world_before"], k)
            # (bit-identical to the discarded encoded result)
        if rows is None and chunk is None:
            if not encoded:
                diffs = pending["buf"]
            sync0 = time.perf_counter()
            host_diffs = (self.stepper.fetch_diffs or np.asarray)(diffs)
            t_host = time.perf_counter()
            pending["sync_s"] = (pending.get("sync_s", 0.0)
                                 + t_host - sync0)
            if chunk_mode and np.asarray(host_diffs).dtype == np.uint32:
                from gol_tpu.parallel.stepper import (
                    sparse_chunk_from_dense,
                )

                chunk = sparse_chunk_from_dense(np.asarray(host_diffs))
                if self.stepper.offers("step_n_with_diffs_sparse"):
                    counts_c = chunk[0]
                    self._adapt_sparse_cap(
                        int(counts_c.max()) if counts_c.size else 0
                    )
            else:
                rows = [host_diffs[i] for i in range(k)]
                self._observe_diff_activity(rows)
            pending["host_extra_s"] = (pending.get("host_extra_s", 0.0)
                                       + time.perf_counter() - t_host)
        # Pipelined spans overlap at dispatch time; clamping each
        # span's start to the previous span's end keeps them
        # disjoint so Timeline's busy_seconds <= wall invariant
        # (and the spans-sum semantics) survive the overlap.
        now = time.perf_counter()
        start = max(pending["tick"], self._last_diff_span_end)
        self._last_diff_span_end = now
        _METRICS.dispatches["diffs"].inc()
        _METRICS.turns["diffs"].inc(k)
        _METRICS.dispatch_seconds["diffs"].observe(now - start)
        _charge_legacy(now - start, k)
        tracing.add_span(
            "engine.dispatch", "engine",
            time.time() - (now - start), now - start,
            {"kind": "diffs", "turn": turn + k, "turns": k},
        )
        if self.timeline:
            self.timeline.record(turn + k, k, now - start, "diffs")
        self._commit(turn + k, new_world, count)
        if chunk is not None:
            # Chunk-granular emission: the whole decoded stack as ONE
            # event, atomically — no mid-emission window for syncs to
            # defer around, no per-turn Python objects.
            emit_tick = time.perf_counter()
            counts_c, bitmaps_c, words_c = chunk
            self.events.put(FlipChunk(
                turn + k, first_turn=turn + 1, counts=counts_c,
                bitmaps=bitmaps_c, words=words_c,
            ))
            emit_dt = time.perf_counter() - emit_tick
            _METRICS.host_seconds.observe(emit_dt)
            tracing.add_span("engine.emit", "engine",
                             time.time() - emit_dt, emit_dt,
                             {"turns": k, "turn": turn + k, "chunk": 1})
            device.observe_split(
                pending.get("enqueue_s"), pending.get("sync_s"),
                emit_dt + pending.get("host_extra_s", 0.0),
            )
            turn += k
            self._throttle_events()
            self._maybe_autosave(turn, new_world)
            if (self._ride_cycles is not None and self._ride is None
                    and self.p.autosave_turns <= 0
                    and self._ride_cycles.observe(turn, new_world)
                    is not None):
                # The anchor walk proved the board revisits an earlier
                # state: schedule a period probe at the next loop
                # boundary (never mid-consume — the pipeline may hold
                # an in-flight chunk).
                self._ride_probe_due = True
            return turn
        # Sync requests must NOT be serviced while this chunk's rows
        # are mid-emission: a BoardSync carries the committed turn+k
        # world, and landing between row i and i+1 would put rows for
        # OLDER turns after it in the stream — XOR consumers would
        # double-apply them onto the newer board, and the gens level
        # grid would be reseeded to a state the remaining rows then
        # wrongly re-age. _service_requests defers syncs while set.
        self._emitting = True
        emit_tick = time.perf_counter()
        try:
            for i, row in enumerate(rows):
                t = turn + 1 + i
                self._emit_turn_flips(t, self._diff_mask(row))
                self.events.put(TurnComplete(t))
                if (i & 31) == 31:
                    # Backpressure per ~32 turns, not per chunk: a slow
                    # consumer otherwise sees DIFF_CHUNK-sized bursts
                    # between throttle checks (ADVICE r4). Verbs
                    # serviced here stamp `t`, the last emitted turn.
                    self._throttle_events(t)
        finally:
            self._emitting = False
            emit_dt = time.perf_counter() - emit_tick
            _METRICS.host_seconds.observe(emit_dt)
            tracing.add_span("engine.emit", "engine",
                             time.time() - emit_dt, emit_dt,
                             {"turns": k, "turn": turn + k})
            # The device-vs-host split of this dispatch, at the
            # boundaries the chunk already crossed: enqueue (the
            # dispatch call returning), sync (the fetched buffers
            # materialising = device work + transfer), host (row
            # DECODE — accumulated in host_extra_s by the decode
            # paths — plus the fan-out above) — gol_tpu.obs.device.
            device.observe_split(
                pending.get("enqueue_s"), pending.get("sync_s"),
                emit_dt + pending.get("host_extra_s", 0.0),
            )
        turn += k
        self._throttle_events()
        self._maybe_autosave(turn, new_world)
        return turn

    def _decode_sparse(self, pending: dict):
        """Sparse rows of a dispatched chunk -> dense word rows, or
        None when any row was truncated (cap overflow)."""
        from gol_tpu.parallel.stepper import sparse_decode_rows

        cap = pending["sparse_cap"]
        sync0 = time.perf_counter()
        host = np.ascontiguousarray(np.asarray(pending["buf"])).view(np.uint32)
        t_host = time.perf_counter()
        pending["sync_s"] = t_host - sync0
        counts = host[:, 0]
        max_m = int(counts.max()) if counts.size else 0
        if max_m > cap:
            return None
        hw, w = self.p.image_height // 32, self.p.image_width
        rows = [
            words.reshape(hw, w)
            for words in sparse_decode_rows(host, hw * w)
        ]
        self._adapt_sparse_cap(max_m)
        # Decode is HOST work: it lands in the split's host leg (via
        # host_extra_s), not in the sync boundary above.
        pending["host_extra_s"] = time.perf_counter() - t_host
        return rows

    def _fetch_compact(self, pending: dict):
        """Materialize a dispatched compact chunk's header stack and
        used value prefix: (header, vals, total) with the sync-split
        and link-cost accounting, or None when the summed counts
        overran the value buffer (overflow — the buffer holds dropped
        writes and must not be trusted). The fetch is the whole point
        of the encoding: 4k + k·nb·4 header bytes plus ~4·Σmₜ value
        bytes, with the fixed per-turn value slab of the sparse rows
        gone."""
        from gol_tpu.parallel.stepper import compact_value_prefix

        sync0 = time.perf_counter()
        header = np.ascontiguousarray(
            np.asarray(pending["buf"])
        ).view(np.uint32)
        pending["sync_s"] = time.perf_counter() - sync0
        total = int(header[:, 0].sum())
        if total > pending["compact_cap"]:
            return None
        fetch_vals = (self.stepper.fetch_compact_values
                      or compact_value_prefix)
        sync0 = time.perf_counter()
        vals = np.asarray(fetch_vals(pending["values"], total))
        if vals.dtype != np.uint32:
            vals = np.ascontiguousarray(vals).view(np.uint32)
        pending["sync_s"] += time.perf_counter() - sync0
        # Actual link cost: the header stack plus the (bucketed) value
        # prefix that was really fetched.
        nbytes = header.nbytes + vals.nbytes
        _METRICS.compact_bytes.inc(nbytes)
        dense = pending["k"] * (self.p.image_height // 32) \
            * self.p.image_width * 4
        if dense:
            _METRICS.compact_ratio.set(round(nbytes / dense, 5))
        return header, vals, total

    def _decode_compact(self, pending: dict):
        """Compact chunk -> dense word rows, or None on overflow."""
        from gol_tpu.parallel.stepper import compact_decode_rows

        got = self._fetch_compact(pending)
        if got is None:
            return None
        header, vals, _total = got
        t_host = time.perf_counter()
        counts = header[:, 0]
        hw, w = self.p.image_height // 32, self.p.image_width
        rows = [
            words.reshape(hw, w)
            for words in compact_decode_rows(header, vals, hw * w)
        ]
        self._adapt_sparse_cap(int(counts.max()) if counts.size else 0)
        pending["host_extra_s"] = time.perf_counter() - t_host
        return rows

    def _chunk_from_compact(self, pending: dict):
        """Compact chunk -> the (counts, bitmaps, values) S-sparse
        triple a FlipChunk carries, or None on overflow. The device
        layout IS the chunk layout — no dense scatter, just slices;
        this is what makes the batched watched path's engine side
        nearly free."""
        got = self._fetch_compact(pending)
        if got is None:
            return None
        header, vals, total = got
        t_host = time.perf_counter()
        counts = header[:, 0].astype(np.int64)
        self._adapt_sparse_cap(int(counts.max()) if counts.size else 0)
        pending["host_extra_s"] = time.perf_counter() - t_host
        return counts, header[:, 1:], vals[:total]

    def _chunk_from_sparse(self, pending: dict):
        """Fixed-width sparse rows -> the FlipChunk S-sparse triple,
        or None when any row was truncated (cap overflow)."""
        from gol_tpu.parallel.stepper import sparse_bitmap_words

        cap = pending["sparse_cap"]
        sync0 = time.perf_counter()
        host = np.ascontiguousarray(np.asarray(pending["buf"])).view(np.uint32)
        t_host = time.perf_counter()
        pending["sync_s"] = t_host - sync0
        counts = host[:, 0].astype(np.int64)
        if counts.size and int(counts.max()) > cap:
            return None
        hw, w = self.p.image_height // 32, self.p.image_width
        nb = sparse_bitmap_words(hw * w)
        bitmaps = host[:, 1:1 + nb]
        parts = [host[t, 1 + nb:1 + nb + int(m)]
                 for t, m in enumerate(counts) if m]
        values = (np.concatenate(parts) if parts
                  else np.zeros(0, np.uint32))
        self._adapt_sparse_cap(int(counts.max()) if counts.size else 0)
        pending["host_extra_s"] = time.perf_counter() - t_host
        return counts, bitmaps, values

    def _sparse_cap_ceiling(self) -> int:
        total_words = (self.p.image_height // 32) * self.p.image_width
        return total_words // 2

    def _observe_diff_activity(self, rows) -> None:
        """After a plain packed chunk: enable sparse encoding when the
        observed peak changed-word count fits a worthwhile cap."""
        if not self.stepper.offers("step_n_with_diffs_sparse"):
            return
        if not rows or rows[0].dtype != np.uint32:
            return  # dense-mask backends stay on the plain path
        max_words = max(int(np.count_nonzero(r)) for r in rows)
        self._adapt_sparse_cap(max_words)

    def _adapt_sparse_cap(self, max_words: int) -> None:
        """Set the next chunk's cap to a power of two with 2x headroom
        over the observed peak, clamped to the ceiling (where the row is
        still ~2x under the mask). Enabling requires the peak to clear
        the ceiling with 2x margin — activity near the ceiling would
        overflow-and-redo every other chunk. Every cap is a power of
        two (the ceiling clamp rounds DOWN to one), which makes shrink
        hysteresis inherent: pow2(2*peak) < cap requires peak <= cap/4,
        so an oscillating peak can never flip-flop the compiled size
        (each distinct cap is a recompile of the k-turn scan). The
        pow2-floored clamp still covers any peak the enable check
        admits: 2*peak <= ceiling implies peak <= pow2floor(ceiling)."""
        prev = self._sparse_cap
        ceiling = self._sparse_cap_ceiling()
        if ceiling < DIFF_SPARSE_MIN_CAP or 2 * max_words > ceiling:
            self._sparse_cap = None
        else:
            want = (
                max(DIFF_SPARSE_MIN_CAP,
                    1 << (2 * max_words - 1).bit_length())
                if max_words
                else DIFF_SPARSE_MIN_CAP
            )
            self._sparse_cap = min(want, 1 << (ceiling.bit_length() - 1))
        if self._sparse_cap != prev:
            # An encoding decision is timeline-worthy: each distinct
            # cap recompiles the k-turn scan, and a flapping cap is
            # exactly the pathology a post-mortem should show.
            tracing.event("engine.sparse_cap", "engine",
                          cap=self._sparse_cap, peak=max_words)

    def _seed_gens_states(self, host_levels) -> None:
        """(Re)anchor the level-mode state grid to a known gray board —
        at load/resume and on every serviced BoardSync, so a stale grid
        from a detached stretch can never leak into a fresh attach."""
        if self._gens_levels is not None:
            from gol_tpu.ops.generations import states_from_levels

            self._gens_levels["states"] = states_from_levels(
                np.asarray(host_levels), self._gens_levels["rule"]
            )

    def _emit_turn_flips(self, t: int, mask) -> None:
        """One turn's flip events from a dense changed mask, in the
        consumer's negotiated form: level batches (multi-state), plain
        batches, or per-cell CellFlipped (the reference contract)."""
        if self._gens_levels is not None:
            g = self._gens_levels
            m = np.asarray(mask) != 0
            states = g["states"]
            states[m] = g["next"][states[m]]
            self.events.put(
                FlipBatch(t, xy_from_mask(m), levels=g["lut"][states[m]])
            )
        elif self.emit_flip_batches:
            self.events.put(FlipBatch(t, xy_from_mask(mask)))
        else:
            for cell in cells_from_mask(mask):
                self.events.put(CellFlipped(t, cell))

    def _diff_mask(self, diff) -> np.ndarray:
        """One turn's diff row as a dense mask — packed uint32 word-rows
        (bitlife layout) are unpacked, dense bool/uint8 pass through."""
        if diff.dtype == np.uint32:
            from gol_tpu.ops.bitlife import unpack_np

            return unpack_np(diff, self.p.image_height)
        return diff

    def _diff_cells(self, diff) -> list:
        """Flipped Cells of one turn's diff row."""
        return cells_from_mask(self._diff_mask(diff))

    # --- services ---

    def _alive_mask(self, host_world):
        """Alive-cell mask of a fetched (gray-level) world for event
        payloads: nonzero for two-state rules, the stepper's own notion
        for multi-state backends where dying cells are nonzero grays."""
        if self.stepper.offers("alive_mask"):
            return self.stepper.alive_mask(host_world)
        return host_world

    def _commit(self, turn: int, world, count) -> None:
        self._committed = (turn, world, count)
        _METRICS.committed_turn.set(turn)
        # One black-box note per committed dispatch: the flight
        # recorder's dump contract — its last recorded turn is within
        # one dispatch chunk of the engine's committed turn — rests on
        # exactly this line.
        flight.note("engine.commit", turn=turn)

    def _service_requests(self) -> None:
        """Engine thread: answer all pending cross-thread requests by
        realising committed device values (D2H copies of results already
        computed inside the step program — no new device work)."""
        with self._req_lock:
            if self._emitting:
                # Mid-chunk emission: defer sync requests to the next
                # dispatch boundary — a BoardSync of the committed
                # turn+k world between rows for older turns would make
                # consumers double-apply them (see _diff_consume).
                reqs = [r for r in self._requests if r[0] != "sync"]
                self._requests = [r for r in self._requests if r[0] == "sync"]
            else:
                reqs, self._requests = self._requests, []
        if not reqs:
            return
        turn, world, count = self._committed
        if count is not None:
            self._last_pair = (turn, int(count))
            _METRICS.alive_cells.set(self._last_pair[1])
        for kind, ev, box in reqs:
            if kind == "sync":
                if world is not None and not self._finished.is_set():
                    host = self.stepper.fetch(world)
                    self._seed_gens_states(host)
                    self.events.put(BoardSync(turn, host, box["token"]))
                    if box["enable_flips"]:
                        self.emit_flips = True
            else:
                box["turn"], box["count"] = self._last_pair
            if ev is not None:
                ev.set()

    def _ticker(self) -> None:
        """AliveCellsCount every tick (ref: gol/distributor.go:283-302) —
        but as a *requester*: the engine thread does the device reads.

        The request timeout is short on purpose: the engine can only
        service requests between dispatches, and the first dispatch on a
        cold TPU includes a 20-40s XLA compile. The reference contract
        is a report within 5s of a cold start (ref: count_test.go:30-38),
        and its ticker satisfies it by reading the last committed state
        (ref: gol/distributor.go:290-295); `alive_count_now` does the
        same on timeout — it falls back to the last consistent
        (turn, count) pair, which is the turn-0 count until the first
        dispatch commits. Stale-but-consistent beats late.

        The first wait is capped at 1s (then the regular cadence): the
        5s first-report budget also covers backend/tunnel init, and the
        liveness signal should not queue behind it."""
        wait = min(self.p.tick_seconds, 1.0)
        while not self._ticker_stop.wait(wait):
            wait = self.p.tick_seconds
            if self._paused:
                # The reference's ticker blocks on the pause mutex
                # (ref: gol/distributor.go:291-294) — no counts while paused.
                continue
            timeout = min(0.5, self.p.tick_seconds / 2)
            turn, count = self.alive_count_now(timeout=timeout)
            if not self._ticker_stop.is_set():
                self.events.put(AliveCellsCount(turn, count))

    def _poll_keys(self, turn: int) -> None:
        if self.keypresses is None:
            return
        while True:
            try:
                key = self.keypresses.get_nowait()
            except queue.Empty:
                return
            self._handle_key(key, turn)
            if self._paused and not self._emitting:
                # Block on further keys while paused (ref: gol/distributor.go:264-277),
                # but keep servicing count requests so alive_count_now
                # callers aren't stalled for their whole timeout. A
                # pause entered MID-CHUNK-EMISSION must not block here:
                # sync servicing is deferred while _emitting (stream
                # ordering), so waiting would starve attaches for the
                # whole pause — finish the chunk's rows first, then the
                # run loop's boundary poll blocks with syncs live.
                while self._paused and self._stop_reason is None:
                    self._service_requests()
                    try:
                        key = self.keypresses.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    self._handle_key(key, turn)

    def _handle_key(self, key: str, turn: int) -> None:
        if key == "s":
            turn_now, world, _ = self._committed
            self._write_snapshot(turn_now, world)
        elif key in ("q", "k"):
            self._stop_reason = key
            self._paused = False
        elif key == "p":
            self._paused = not self._paused
            # Byte-for-byte the reference's pause prints: the current
            # turn on pause, "Continuing" on resume, from the engine
            # itself (ref: gol/distributor.go:264-277 — fmt.Println of
            # *turn, then of the literal).
            print(turn if self._paused else "Continuing")
            self.events.put(
                StateChange(turn, State.PAUSED if self._paused else State.EXECUTING)
            )

    def _throttle_events(self, turn: Optional[int] = None) -> None:
        """Producer-side backpressure: when an event consumer lags far
        behind (an engine can emit millions of TurnCompletes/s; a wire
        broadcaster drains tens of thousands), wait for the backlog to
        drain before dispatching more turns. The reference gets this
        from its 1000-slot channel buffer blocking the sender
        (ref: main.go:53); here the wait loop stays interruptible —
        stop/'q'/'k' and count requests are still serviced — so a
        vanished consumer can never wedge shutdown the way a hard
        blocking put would.

        A backlog with NO consumption progress is a run whose queue
        nobody drains (library callers may drop the queue entirely) —
        waiting on it would hang a run that used to complete, so after
        5s without a single get() the throttle disarms for the rest of
        the run and the queue just grows, the pre-backpressure
        behavior.

        `turn` stamps any StateChange a serviced verb emits; callers
        throttling mid-emit pass the last turn whose events are out
        (the committed turn may be a whole chunk ahead of the
        stream)."""
        if self._throttle_disabled:
            return
        at = self._committed[0] if turn is None else turn
        _METRICS.queue_depth.set(self.events.qsize())
        stalled_since = None
        throttled = False
        last_consumed = self.events.consumed
        # Chunk events are k-turn ARRAYS, not per-turn objects: a
        # backlog of 10k of them would hold gigabytes, so the depth
        # limit drops to a few dozen chunks (still tens of thousands
        # of turns of slack for the consumer).
        limit = 32 if self._chunk_mode() else 10_000
        while (
            self.events.qsize() > limit
            and self._stop_reason is None
            and not self.events.closed
        ):
            if not throttled:
                throttled = True
                _METRICS.throttle_stalls.inc()
            self._service_requests()
            self._poll_keys(at)
            time.sleep(0.005)
            consumed = self.events.consumed
            if consumed != last_consumed:
                last_consumed = consumed
                stalled_since = None
            elif stalled_since is None:
                stalled_since = time.monotonic()
            elif time.monotonic() - stalled_since > 5.0:
                self._throttle_disabled = True
                return

    def _maybe_autosave(self, turn: int, world) -> None:
        """Periodic auto-checkpoint between dispatches. Snapshot cadence
        is by completed turns and/or wall seconds (Params.autosave_*);
        the final turn is skipped — normal completion writes it anyway
        (ref: gol/distributor.go:180-191). The write is async (IO
        thread) and crash-atomic (io/pgm.py), so the turn loop pays only
        the device fetch."""
        p = self.p
        if (p.autosave_turns <= 0 and p.autosave_seconds <= 0) or turn >= p.turns:
            return
        due = (
            p.autosave_turns > 0 and turn - self._autosave_turn >= p.autosave_turns
        ) or (
            p.autosave_seconds > 0
            and time.monotonic() - self._autosave_time >= p.autosave_seconds
        )
        if not due:
            return
        self._autosave_turn = turn
        self._autosave_time = time.monotonic()
        self._write_snapshot(turn, world)

    def _write_snapshot(self, turn: int, world, wait: bool = False) -> None:
        """Write out/<W>x<H>x<turn>.pgm and emit ImageOutputComplete once
        the bytes land (ref: gol/distributor.go:229-241, filename
        convention ref: gol/distributor.go:181,230)."""
        name = self.p.output_name(turn)
        host = self.stepper.fetch(world)
        done = threading.Event()

        def on_complete(n: str, exc: Optional[BaseException]) -> None:
            if exc is None:
                self.events.put(ImageOutputComplete(turn, n))
            done.set()

        self.io.write(name, host, on_complete)
        if wait:
            done.wait(timeout=30)


def run(
    params: Params,
    keypresses: Optional[queue.Queue] = None,
    events: Optional[EventQueue] = None,
    **engine_kwargs,
) -> EventQueue:
    """Start the engine and return its event queue — the public entry
    point mirroring `gol.Run(p, events, keyPresses)` (ref: gol/gol.go:12-41)."""
    engine = Engine(params, events=events, keypresses=keypresses, **engine_kwargs)
    engine.start()
    return engine.events
