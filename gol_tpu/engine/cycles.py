"""Exact cycle fast-forward for astronomically long runs.

The reference's default run is 10^10 turns (ref: main.go:20 — the
`-turns` default), which no engine steps one by one; yet finite Life
boards are eventually periodic (the 512² golden board settles into a
period-2 oscillation after ~turn 10,000, ref: count_test.go:45-51).
Periodicity makes fast-forward *bit-exact with zero approximation*:
if `world(t) == world(a)` then `world(t + k) == world(a + k)` for all
k, so the remaining turns collapse modulo `m = t - a` and the final
board is reached by stepping `remaining % m` more turns. Equality is a
full device-side board compare (one fused reduce, no hashing) — a hit
can never be spurious.

Detection is a Brent-style anchor walk at dispatch granularity: hold
an anchor state, compare the committed world against it at a wall-clock
cadence (each compare costs one scalar realization — the same price as
a ticker sample), and double the anchor's lease each refresh so some
anchor eventually lands inside the cycle with a lease long enough to
see a full period. Comparing at multiples of the dispatch chunk finds
a *multiple* of the true period (chunks are powers of two, so any
even-period oscillation — the overwhelmingly common case — is caught
on the first in-cycle compare); a multiple is all fast-forward needs.

Opt-in via Params.cycle_detect / `--cycle-detect`: the observable event
stream (ticker samples, snapshots, the final board) stays exact, but
turn numbers leap, which a consumer expecting dense TurnComplete
cadence might not want — and the detector only ever runs on the fused
headless path where no such consumer is attached.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


class CycleDetector:
    """Feed `observe(turn, world)` after each committed dispatch; it
    returns a period multiple `m` once `world` provably equals an
    earlier committed state `m` turns back, else None."""

    def __init__(self, interval_seconds: float = 2.0):
        self.interval = interval_seconds
        self._equal = jax.jit(lambda a, b: jnp.array_equal(a, b))
        self._anchor = None
        self._anchor_turn = -1
        self._lease = 1  # compares until the anchor is replaced
        self._used = 0
        self._next_check = time.monotonic() + interval_seconds

    def observe(self, turn: int, world) -> int | None:
        # In a multi-process SPMD job every device program must be
        # broadcast to all workers (parallel/multihost.py mirrors the
        # stepper's dispatches); the compare below is not mirrored, and
        # an unmirrored program over a globally-sharded array would
        # strand the other processes at a collective rendezvous. Checked
        # live (not latched at construction) because
        # jax.distributed.initialize() may run after this detector is
        # built.
        if jax.process_count() > 1:
            return None
        now = time.monotonic()
        if now < self._next_check:
            return None
        self._next_check = now + self.interval
        if self._anchor is None:
            self._anchor, self._anchor_turn = world, turn
            return None
        # One scalar realization; the compare itself ran on device.
        if bool(self._equal(self._anchor, world)):
            return turn - self._anchor_turn
        self._used += 1
        if self._used >= self._lease:
            # Brent doubling: a longer-lived anchor further along the
            # orbit — eventually one sits inside the cycle with a lease
            # covering a full period.
            self._anchor, self._anchor_turn = world, turn
            self._lease *= 2
            self._used = 0
        return None
