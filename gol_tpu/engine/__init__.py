from gol_tpu.engine.distributor import (
    Engine,
    EventQueue,
    register_live_engine,
    run,
)

__all__ = ["Engine", "EventQueue", "register_live_engine", "run"]
