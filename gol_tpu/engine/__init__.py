from gol_tpu.engine.distributor import Engine, EventQueue, run

__all__ = ["Engine", "EventQueue", "run"]
