"""gol_tpu — a TPU-native distributed Game of Life framework.

A ground-up JAX/XLA re-design of the capabilities of the reference Go
implementation (uk.ac.bris.cs/gameoflife): a concurrent + distributed
cellular-automaton engine with a typed event stream, PGM storage I/O,
an interactive controller (pause / snapshot / quit / kill), live
alive-count telemetry, a visualiser protocol, and multi-device scaling
via row-strip sharding with ring halo exchange (`lax.ppermute` over ICI)
instead of the reference's goroutine row-farm (ref: gol/distributor.go).

Public surface mirrors the reference's single exported entry point
`gol.Run(p, events, keyPresses)` (ref: gol/gol.go:12-41):

    from gol_tpu import Params, run
    events = run(Params(turns=100, threads=1, image_width=16, image_height=16))
    for ev in events: ...

Import of this package must not initialise a JAX backend; tests set
JAX_PLATFORMS/XLA_FLAGS in conftest before anything touches jax.
"""

from gol_tpu.params import Params
from gol_tpu.events import (
    AliveCellsCount,
    CellFlipped,
    Event,
    FinalTurnComplete,
    FlipBatch,
    ImageOutputComplete,
    State,
    StateChange,
    TurnComplete,
)

__all__ = [
    "Params",
    "Event",
    "AliveCellsCount",
    "ImageOutputComplete",
    "StateChange",
    "CellFlipped",
    "FlipBatch",
    "TurnComplete",
    "FinalTurnComplete",
    "State",
    "run",
]

__version__ = "0.4.0"


def run(params, keypresses=None, events=None, **kwargs):
    """Start the engine; returns the event queue (see engine.distributor).

    Deferred import so that `import gol_tpu` stays backend-free.
    """
    from gol_tpu.engine.distributor import run as _run

    return _run(params, keypresses=keypresses, events=events, **kwargs)
