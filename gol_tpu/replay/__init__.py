"""gol_tpu.replay — the replay plane (ROADMAP item 2, docs/REPLAY.md).

Every session a seekable recording; recorded runs served at zero
engine dispatches:

- `log` — the append-only segment log (verbatim FBATCH + BoardSync
  keyframe payloads, keyframe-indexed by filename, torn-tail
  tolerant, size-bounded) and its decode helpers (`seek_frames`,
  `board_at`).
- `recorder` — `RecorderSink`, the ephemeral session sink that tapes
  a live session (`--serve --sessions --record`).
- `server` — `ReplayServer` (`--replay DIR`), the static broadcast
  tier serving recordings to N observers with zero engine dispatches,
  composing under the PR 12 relay tree; `serve_seek`, the one seek
  implementation both serving planes share.

`ReplayServer` is imported lazily: the log/decoder half stays light
(numpy + wire only) for `obs.report merge --replay-to`.
"""

from gol_tpu.replay.log import (
    KEYFRAME_TURNS,
    SegmentLog,
    board_at,
    find_recordings,
    last_turn,
    replay_dir,
    scan_segments,
    seek_frames,
)

__all__ = [
    "KEYFRAME_TURNS",
    "RecorderSink",
    "ReplayServer",
    "SegmentLog",
    "board_at",
    "find_recordings",
    "last_turn",
    "replay_dir",
    "scan_segments",
    "seek_frames",
    "serve_seek",
]


def __getattr__(name):
    if name == "ReplayServer" or name == "serve_seek":
        from gol_tpu.replay import server

        return getattr(server, name)
    if name == "RecorderSink":
        from gol_tpu.replay.recorder import RecorderSink

        return RecorderSink
    raise AttributeError(name)
