"""RecorderSink — a session's wire stream, taped.

One more Sink on the session (gol_tpu.sessions.Sink): chunk-granular
(`batch_turns` > 0), so the manager hands it the same S-sparse device
chunks every batching watcher gets, and it writes the ENCODED FBATCH
frames plus periodic BoardSync keyframes to a SegmentLog — the engine
encodes once per chunk whether anyone is watching live or not, and the
bytes on disk are the bytes a replay server later forwards verbatim
(zero re-encode end to end).

The sink is EPHEMERAL (`ephemeral = True`): it never counts as a
watcher for the hibernation policy — an idle recorded session still
parks (the manager closes the recorder with reason "parked", the log's
last segment stays durable), and the next attach re-creates the
recorder off the rehydrated board (a fresh keyframe at the parked
turn, so the log never records the gap that never stepped).

Callbacks run on the dispatching engine thread; disk appends are
buffered writes + flush (no fsync — the torn-tail discipline of
log.py makes a crash lose at most the tail record)."""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from gol_tpu.distributed import wire
from gol_tpu.obs import tracing
from gol_tpu.replay.log import KEYFRAME_TURNS, SegmentLog
from gol_tpu.sessions.manager import SessionManager, Sink

__all__ = ["RecorderSink"]


class RecorderSink(Sink):
    #: Never a watcher for park/idle policy (see module docstring).
    ephemeral = True
    want_flips = True

    def __init__(self, manager: SessionManager, sid: str,
                 width: int, height: int, log: SegmentLog,
                 on_closed: Optional[Callable[[str, str], None]] = None):
        self._manager = manager
        self.sid = sid
        self._width = width
        self._height = height
        self.log = log
        self._on_closed = on_closed
        #: Chunk-granular at the keyframe cadence: every recorded
        #: frame covers at most one keyframe interval, which is what
        #: bounds how far past a requested turn a seek can land.
        self.batch_turns = log.keyframe_turns

    # --- Sink protocol (engine thread) ---

    def on_sync(self, sid: str, turn: int, board) -> None:
        """Attach/resync raster -> a keyframe starting a new segment
        (also the crash-restart cut point: stale future segments are
        dropped by start_segment)."""
        self.log.start_segment(
            turn, wire.board_to_frame(turn, board, 0), time.time()
        )
        tracing.event("replay.keyframe", "wire", session=sid, turn=turn)

    def on_flip_chunk(self, sid: str, first_turn: int, counts,
                      bitmaps, words) -> None:
        from gol_tpu.distributed.server import encode_batch_frames

        k = len(counts)
        frames = encode_batch_frames(
            counts, bitmaps, words, first_turn,
            self._width, self._height, self.batch_turns, time.time(),
        )
        ts = time.time()
        for f in frames:
            span = (first_turn, first_turn + k - 1)
            self.log.append(f, ts, span[1])
        self._maybe_keyframe(first_turn + k - 1)

    def on_flips(self, sid: str, turn: int, coords) -> None:
        """Per-turn fallback (a non-packed bucket, or a mixed bucket
        whose dispatch ran the per-turn demux): one single-turn FBATCH
        frame — the same on-disk grammar either way."""
        bitmap, wordvals = wire.coords_to_words(
            coords, self._width, self._height
        )
        _, nb = wire.grid_words(self._width, self._height)
        frame = wire.flip_batch_to_frame(
            turn, nb, np.asarray([len(wordvals)], np.uint32),
            bitmap.reshape(1, -1), wordvals, time.time(),
        )
        self.log.append(frame, time.time(), turn)

    def on_turn(self, sid: str, turn: int) -> None:
        # Per-turn fallback path: callbacks for a whole chunk run
        # AFTER the chunk committed, so _fetch_board always returns
        # the POST-chunk board — cutting a keyframe mid-chunk would
        # stamp that board with an earlier turn and every frame after
        # it would double-apply on replay. Only the chunk's final
        # turn (== the session's committed turn) may cut one.
        if turn == self._manager.peek_turn(self.sid):
            self._maybe_keyframe(turn)

    def _maybe_keyframe(self, turn: int) -> None:
        if not self.log.due_keyframe(turn):
            return
        # Engine thread owns the device (the _SessionSink drain-resync
        # precedent): fetch the post-chunk board directly.
        board = self._manager._fetch_board(self.sid)
        self.log.start_segment(
            turn, wire.board_to_frame(turn, board, 0), time.time()
        )
        tracing.event("replay.keyframe", "wire", session=self.sid,
                      turn=turn)

    def on_close(self, sid: str, reason: str) -> None:
        self.log.close()
        if self._on_closed is not None:
            self._on_closed(sid, reason)
