"""Segment log — a session's wire stream as seekable bytes on disk.

The wire already emits the perfect log format: `_TAG_FBATCH` frames
are SELF-CONTAINED (the turn-axis delta chain never crosses a frame —
wire.py's invariant) and BoardSync rasters are complete state, so a
recording is just the encoded frame payloads written VERBATIM — the
PR 12 zero-re-encode invariant extended to disk. Serving a recording
is a byte-copy problem (gol_tpu.replay.server); decoding one is the
ordinary client apply path (`board_at` below reproduces it host-side
for time-travel debugging).

Layout (one directory per recording, `<session-dir>/replay/`):

    seg-<turn:016d>.glog        one SEGMENT per keyframe interval

A segment starts with its keyframe — a `_TAG_BOARD` payload at the
turn in the filename — followed by the FBATCH payloads for the turns
after it. Records are length-prefixed and wall-clock stamped:

    <u32 payload_len> <f64 wall_ts> <payload bytes>

The filename IS the keyframe index: "nearest keyframe <= T" is a
directory listing, no sidecar index to corrupt. Crash consistency is
by construction: records are appended and flushed in order, so a
SIGKILL leaves at most a torn TAIL record, which `read_records`
detects by length and discards — serving continues from the last good
frame (the wire-fuzz suite pins this). The log is size-bounded:
oldest segments are evicted once `max_bytes` is exceeded (the current
segment is never evicted), so a viral board's history is a ring, not
a disk leak.
"""

from __future__ import annotations

import contextlib
import os
import re
import struct
from typing import Iterator, Optional

import numpy as np

from gol_tpu import obs
from gol_tpu.distributed import wire

__all__ = [
    "SegmentLog",
    "apply_fbatch_slice",
    "board_at",
    "fbatch_span",
    "find_recordings",
    "last_turn",
    "read_records",
    "replay_dir",
    "scan_segments",
    "seek_frames",
]

#: Record header: payload length, emit wall clock. The payload is a
#: raw wire frame payload (no 4-byte wire length prefix — that is
#: transport framing, re-applied at serve time by `_Conn.send_raw`).
_REC = struct.Struct("<Id")

_SEG = re.compile(r"^seg-(\d{16})\.glog$")

#: Default keyframe cadence in turns — the seek granularity AND the
#: catch-up cost of a cold attach (one raster + up to this many turns
#: of deltas).
KEYFRAME_TURNS = 256


class _LogMetrics:
    """Writer-side counters (issue catalog: docs/REPLAY.md)."""

    def __init__(self):
        self.segments = obs.counter(
            "gol_tpu_replay_segments_written",
            "Replay-log segments started (one per keyframe)",
        )
        self.bytes = obs.counter(
            "gol_tpu_replay_bytes_written",
            "Replay-log bytes appended (records incl. headers)",
        )
        self.evicted = obs.counter(
            "gol_tpu_replay_segments_evicted_total",
            "Oldest segments evicted by the max-bytes bound",
        )
        self.keyframe_turns = obs.gauge(
            "gol_tpu_replay_keyframe_turns",
            "Configured keyframe cadence of this process's recorders "
            "(turns between BoardSync keyframes = seek granularity)",
        )


_METRICS = _LogMetrics()


def replay_dir(session_dir: str | os.PathLike) -> str:
    """Where a session's recording lives: `<session-dir>/replay/` —
    alongside the PR 7 checkpoints, inside the same crash-consistency
    story (tombstone-gated remnant clearing covers it)."""
    return os.path.join(os.fspath(session_dir), "replay")


class SegmentLog:
    """Append-only writer for one recording. NOT thread-safe — the
    recorder calls it from the one dispatching (engine) thread, the
    same single-writer discipline every device structure rides."""

    def __init__(self, root: str | os.PathLike,
                 keyframe_turns: int = KEYFRAME_TURNS,
                 max_bytes: Optional[int] = None):
        self.root = os.fspath(root)
        self.keyframe_turns = max(1, int(keyframe_turns))
        self.max_bytes = max_bytes
        _METRICS.keyframe_turns.set(self.keyframe_turns)
        self._f = None
        self._seg_start = -1
        #: Last turn any appended frame covered (the keyframe's turn
        #: until frames arrive).
        self.last_turn = -1
        self._total_bytes = 0
        with contextlib.suppress(OSError):
            self._total_bytes = sum(
                os.path.getsize(p) for _, p in scan_segments(self.root)
            )

    # --- writing ---

    def _write_record(self, payload: bytes, ts: float) -> None:
        rec = _REC.pack(len(payload), ts) + payload
        self._f.write(rec)
        # Flush per record: a concurrent seek reads the file the
        # recorder is appending, and must see whole records (a torn
        # OS-level tail is discarded by the reader either way).
        self._f.flush()
        self._total_bytes += len(rec)
        _METRICS.bytes.inc(len(rec))

    def start_segment(self, turn: int, payload: bytes,
                      ts: float) -> None:
        """Begin a new segment with its keyframe (a `_TAG_BOARD`
        payload at `turn`). Any existing segment at or past this turn
        is DROPPED first: a crash-restarted engine resumes from its
        checkpoint, and frames the dead incarnation recorded beyond
        that turn describe a future that never happened."""
        self.close_segment()
        os.makedirs(self.root, exist_ok=True)
        for seg_turn, path in scan_segments(self.root):
            if seg_turn >= turn:
                with contextlib.suppress(OSError):
                    self._total_bytes -= os.path.getsize(path)
                with contextlib.suppress(OSError):
                    os.unlink(path)
        self._total_bytes = max(0, self._total_bytes)
        path = os.path.join(self.root, f"seg-{turn:016d}.glog")
        self._f = open(path, "wb")
        self._seg_start = turn
        self.last_turn = turn
        self._write_record(payload, ts)
        _METRICS.segments.inc()
        self._evict()

    def append(self, payload: bytes, ts: float, last_turn: int) -> None:
        """Append one stream frame (FBATCH) covering turns up to
        `last_turn`. Frames before the first keyframe are dropped —
        without a raster beneath them they are undecodable."""
        if self._f is None:
            return
        self._write_record(payload, ts)
        self.last_turn = max(self.last_turn, int(last_turn))

    def due_keyframe(self, turn: int) -> bool:
        return (self._seg_start < 0
                or turn - self._seg_start >= self.keyframe_turns)

    def _evict(self) -> None:
        if self.max_bytes is None:
            return
        while self._total_bytes > self.max_bytes:
            segs = scan_segments(self.root)
            if len(segs) <= 1:
                return  # never evict the current (only) segment
            _, oldest = segs[0]
            try:
                size = os.path.getsize(oldest)
                os.unlink(oldest)
            except OSError:
                return
            self._total_bytes -= size
            _METRICS.evicted.inc()

    def close_segment(self) -> None:
        if self._f is not None:
            with contextlib.suppress(OSError):
                self._f.close()
            self._f = None

    def close(self) -> None:
        self.close_segment()


# --- reading (tolerant: every path here runs on freshly crashed trees) ---


def scan_segments(root: str | os.PathLike) -> "list[tuple[int, str]]":
    """Sorted [(keyframe_turn, path)] of a recording directory; an
    unreadable/missing directory is an empty recording, never an
    exception."""
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    out = []
    for name in names:
        m = _SEG.match(name)
        if m:
            out.append((int(m.group(1)),
                        os.path.join(os.fspath(root), name)))
    out.sort()
    return out


def read_records(path: str) -> "list[tuple[float, bytes]]":
    """Every whole record of one segment, in order. A torn tail — a
    header or payload cut short by a crash, or a header claiming an
    implausible length — ends the list silently: everything before the
    tear is intact (records are appended and flushed in order), and
    serving continues from the last good frame."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return []
    out = []
    off = 0
    while off + _REC.size <= len(blob):
        n, ts = _REC.unpack_from(blob, off)
        if n > wire.MAX_FRAME or off + _REC.size + n > len(blob):
            break  # torn or hostile tail: discard from here
        out.append((ts, blob[off + _REC.size:off + _REC.size + n]))
        off += _REC.size + n
    return out


def iter_records(root: str | os.PathLike
                 ) -> Iterator[tuple[float, bytes]]:
    for _, path in scan_segments(root):
        yield from read_records(path)


def fbatch_span(payload: bytes) -> "Optional[tuple[int, int]]":
    """(first_turn, last_turn) of an FBATCH payload, or None for any
    other (or malformed) record — header-only, no blob decode."""
    if not payload or payload[0] != wire._TAG_FBATCH \
            or len(payload) < wire._FBATCH_HDR.size:
        return None
    try:
        _, first, k, _, _, _, _, _ = wire._FBATCH_HDR.unpack_from(payload)
    except struct.error:
        return None
    if not 0 < k <= wire.FBATCH_MAX_TURNS:
        return None
    return int(first), int(first) + int(k) - 1


def _is_board(payload: bytes) -> bool:
    return bool(payload) and payload[0] == wire._TAG_BOARD


def seek_frames(root: str | os.PathLike, turn: int
                ) -> "Optional[tuple[int, int, list[bytes]]]":
    """The seek answer for turn T: `(keyframe_turn, landed_turn,
    payloads)` where payloads[0] is the nearest <= T keyframe's board
    payload and the rest are the FBATCH suffix through the frame
    containing T (a straddling frame is included whole — frames are
    indivisible on the wire, so the landing turn may exceed T by less
    than one frame). T before the first keyframe answers from the
    first keyframe (evicted history is gone); T past the end lands at
    the recording's end. None = no usable recording."""
    segs = scan_segments(root)
    best = None
    for i, (seg_turn, path) in enumerate(segs):
        if seg_turn <= turn or best is None:
            best = i
    if best is None:
        return None
    seg_turn, path = segs[best]
    records = read_records(path)
    if not records or not _is_board(records[0][1]):
        # Torn keyframe: walk back to the newest earlier segment
        # whose keyframe still decodes (one step is not enough on a
        # doubly-corrupted tree — serve whatever good history exists).
        for i in range(best - 1, -1, -1):
            got = seek_frames_at(segs[i])
            if got is not None:
                return got
        return None
    payloads = [records[0][1]]
    landed = seg_turn
    for _, payload in records[1:]:
        span = fbatch_span(payload)
        if span is None:
            continue
        first, last = span
        if first > turn:
            break
        payloads.append(payload)
        landed = max(landed, last)
    return seg_turn, landed, payloads


def seek_frames_at(seg: "tuple[int, str]"
                   ) -> "Optional[tuple[int, int, list[bytes]]]":
    """One whole segment as a seek answer (keyframe + every frame) —
    the torn-keyframe fallback and the catch-up primitive."""
    seg_turn, path = seg
    records = read_records(path)
    if not records or not _is_board(records[0][1]):
        return None
    payloads = [r[1] for r in records
                if _is_board(r[1]) or fbatch_span(r[1]) is not None]
    landed = seg_turn
    for p in payloads[1:]:
        span = fbatch_span(p)
        if span is not None:
            landed = max(landed, span[1])
    return seg_turn, landed, payloads


def last_turn(root: str | os.PathLike) -> int:
    """Last decodable turn of a recording (-1 when empty)."""
    segs = scan_segments(root)
    for seg in reversed(segs):
        got = seek_frames_at(seg)
        if got is not None:
            return got[1]
    return -1


def apply_fbatch_slice(board: np.ndarray, msg: dict,
                       upto_turn: int) -> int:
    """Advance a raster by ONE parsed FBATCH frame, applying only
    turns <= `upto_turn` — the partial-frame twin of the client's
    `apply_fbatch_raster` (same odd-repetition XOR math, upper-bounded
    instead of floor-gated), so `board_at` can land EXACTLY on a turn
    inside a frame. Returns the last turn applied (first_turn - 1 when
    the whole frame is past the bound)."""
    h, w = board.shape
    total, nb = wire.grid_words(w, h)
    if msg["nb"] != nb:
        raise wire.WireError(
            f"batch bitmap rows of {msg['nb']} words, this board "
            f"needs {nb}"
        )
    counts = msg["counts"].astype(np.int64)
    k, first = int(msg["k"]), int(msg["first_turn"])
    klim = min(k, upto_turn - first + 1)
    if klim <= 0:
        return first - 1
    dbm, dwords = msg["dbitmaps"], msg["dwords"]
    nzt = np.flatnonzero(counts)
    offs = np.zeros(len(nzt) + 1, np.int64)
    np.cumsum(counts[nzt], out=offs[1:])
    # Net change over turns [0, klim): D[j] appears (klim - j) times
    # in XOR_{t<klim} S[t]; odd repetition counts survive.
    reps = klim - nzt
    sel = np.flatnonzero((reps > 0) & (reps % 2 == 1))
    if sel.size:
        acc = np.zeros(total, np.uint32)
        for i in sel:
            idx = wire._bitmap_indices(dbm[i])
            acc[idx] ^= dwords[offs[i]:offs[i + 1]]
        fw = np.flatnonzero(acc)
        if fw.size:
            bits = (acc[fw, None] >> np.arange(32, dtype=np.uint32)) & 1
            rr, bb = np.nonzero(bits)
            x = fw[rr] % w
            y = (fw[rr] // w) * 32 + bb
            if y.size and int(y.max()) >= h:
                raise wire.WireError("batch mask bit past the board height")
            board[y, x] ^= np.uint8(255)
    return first + klim - 1


def board_at(root: str | os.PathLike, turn: int
             ) -> "Optional[tuple[int, np.ndarray]]":
    """(landed_turn, (H, W) uint8 board) of the recording at the
    nearest recorded state <= `turn` + any partial frame needed to
    land exactly — the time-travel primitive `obs.report merge
    --replay-to` joins with the flight recorder. None when the
    recording has no usable keyframe."""
    got = seek_frames(root, turn)
    if got is None:
        return None
    _, _, payloads = got
    msg = wire.parse_payload(payloads[0])
    landed, board = wire.msg_to_board(msg)
    board = np.array(board, dtype=np.uint8)
    for payload in payloads[1:]:
        fmsg = wire.parse_payload(payload)
        if fmsg.get("t") != "fbatch":
            continue
        landed = max(landed, apply_fbatch_slice(board, fmsg, turn))
    return int(landed), board


def find_recordings(path: str | os.PathLike) -> "dict[str, str]":
    """{recording_id: replay_dir} under `path` — accepts a sessions
    root (`out/sessions`, each `<sid>/replay/`), a single session
    directory, or a bare replay directory of seg files. The flexible
    spelling is what `--replay DIR` takes."""
    path = os.fspath(path)
    if scan_segments(path):
        return {os.path.basename(os.path.dirname(path.rstrip("/")))
                or "recording": path}
    d = replay_dir(path)
    if scan_segments(d):
        return {os.path.basename(path.rstrip("/")) or "recording": d}
    out = {}
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return out
    for name in names:
        d = replay_dir(os.path.join(path, name))
        if scan_segments(d):
            out[name] = d
    return out
