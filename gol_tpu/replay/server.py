"""ReplayServer — a recorded run served as bytes, zero engine dispatches.

The CDN-shaped answer to viral traffic (ROADMAP item 2): N observers
of a popular RECORDED board cost file reads and queue pushes, never a
stepper dispatch — this process does not own a device, does not import
a stepper, and `gol_tpu_engine_dispatches_total` does not exist on its
/metrics (the replay bench lane and scripts/replay_smoke.sh gate on
exactly that).

It is the relay tier with a directory for an upstream: the same wire
protocol (hello/secret/attach-ack, heartbeats + idle eviction, the
PR 7 degradation machinery on the writer pool's queues), the same
zero-re-encode forwarding (`_Conn.send_raw` on the VERBATIM payloads
the recorder wrote), and it composes under the PR 12 relay tree — a
`--relay` node attaches to a replay server exactly as it would to a
live root, so one recording fans out to 10⁵ browsers through the same
broadcast tiers.

Per recording, one PUMP thread walks the segment log and broadcasts
each record to the attached observers, paced by the recorded wall-
clock deltas (the run replays at the speed it happened) or by
`--replay-rate R` turns/s (0 = flat out). Observers attaching
mid-stream catch up from the current segment's keyframe; `{"t":"seek",
"turn":T}` rewinds ONE observer to the nearest <= T keyframe plus the
FBATCH suffix (the same apply path), parks it there (`scrub`), and
`{"t":"seek","turn":"live"}` rejoins the broadcast position.
"""

from __future__ import annotations

import contextlib
import hmac
import logging
import socket
import threading
import time
from typing import Optional

from gol_tpu import obs
from gol_tpu.distributed import wire
from gol_tpu.distributed.server import (
    _Conn,
    _METRICS as _SRV,
    _clamp_batch,
    install_lag_gauge,
    publish_listen_addr,
    remove_lag_gauge,
)
from gol_tpu.obs import flight, tracing
from gol_tpu.obs.freshness import ServerFreshness
from gol_tpu.relay.writerpool import WriterPool
from gol_tpu.replay.log import (
    fbatch_span,
    find_recordings,
    read_records,
    scan_segments,
    seek_frames,
)
from gol_tpu.analysis.concurrency import lockcheck

__all__ = ["ReplayServer"]

log = logging.getLogger(__name__)


class _ReplayMetrics:
    def __init__(self):
        self.recordings = obs.gauge(
            "gol_tpu_replay_recordings",
            "Recordings this replay server is serving (the series "
            "obs.console keys replay rows on)",
        )
        self.serves = obs.counter(
            "gol_tpu_replay_serves_total",
            "Observer attaches served from recordings",
        )
        self.seeks = obs.counter(
            "gol_tpu_replay_seeks_total",
            "Seek verbs answered (live rejoins included)",
        )
        self.turns = obs.counter(
            "gol_tpu_replay_turns_total",
            "Recorded turns pumped through the broadcast position "
            "(feeds the console's turns/s)",
        )
        self.position = obs.gauge(
            "gol_tpu_replay_position_turn",
            "Deepest broadcast position across recordings (the "
            "console's TURN column for replay rows)",
        )
        self.frames = obs.counter(
            "gol_tpu_replay_forwarded_frames_total",
            "Recorded frames enqueued to observers (verbatim bytes, "
            "zero re-encode)",
        )
        self.bytes = obs.counter(
            "gol_tpu_replay_forwarded_bytes_total",
            "Recorded payload bytes enqueued to observers",
        )


_METRICS = _ReplayMetrics()

#: Ceiling on one recorded inter-frame gap honored by timestamp
#: pacing — a recording that idled for an hour (parked session,
#: paused engine) replays the pause as a beat, not an hour.
PACE_GAP_CAP = 5.0


class _Recording:
    """One recording's broadcast state: the pump's position, the
    current segment's payloads (what a mid-stream attach catches up
    from), and the attached observers. `lock` orders catch-up/seek
    serving against the pump's broadcasts — an observer can never see
    a frame from before its own BoardSync."""

    def __init__(self, sid: str, root: str):
        self.sid = sid
        self.root = root
        self.lock = lockcheck.make_lock("_Recording.lock")
        self.conns: "list[_Conn]" = []
        #: Current segment's payloads, keyframe first.
        self.catchup: "list[bytes]" = []
        self.keyframe_turn = -1
        self.turn = -1
        self.started = False
        self.finished = False


class ReplayServer:
    """Serve the recordings under `path` (a sessions root, a session
    dir, or a bare replay dir — log.find_recordings) on the ordinary
    wire protocol, with zero engine dispatches."""

    HELLO_TIMEOUT = 10.0
    DRAIN_TIMEOUT = 5.0
    HB_MISS_LIMIT = 3
    REPLAY_WINDOW = 512  # rid replay entries (the SessionServer bound)

    def __init__(
        self,
        path: str,
        host: str = "127.0.0.1",
        port: int = 8030,
        *,
        secret: Optional[str] = None,
        replay_rate: Optional[float] = None,
        heartbeat_secs: float = 2.0,
        evict_secs: Optional[float] = None,
        max_peers: Optional[int] = None,
        high_water: Optional[int] = None,
        drain_secs: Optional[float] = None,
        retry_after_secs: float = 1.0,
        batch_turns: int = 1024,
        writer_pool_threads: int = 2,
        pump_paused: bool = False,
    ):
        recs = find_recordings(path)
        if not recs:
            raise ValueError(f"no recordings under {path!r} "
                             "(expected seg-*.glog segment logs)")
        self.path = path
        self._recordings = {
            sid: _Recording(sid, root) for sid, root in sorted(recs.items())
        }
        _METRICS.recordings.set(len(self._recordings))
        #: None = pace by recorded timestamps; > 0 = turns/s; 0 = flat
        #: out (bench/smoke mode).
        self.replay_rate = replay_rate
        self._secret = secret
        self.max_peers = max_peers
        self.high_water = high_water
        self.drain_secs = drain_secs
        self.retry_after_secs = max(0.0, retry_after_secs)
        self.batch_turns = max(0, batch_turns)
        self.heartbeat_secs = max(0.0, heartbeat_secs)
        self.evict_secs = (evict_secs if evict_secs is not None
                           else 3.0 * self.heartbeat_secs)
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        publish_listen_addr(self.address)
        #: Freshness plane: observers age against their recording's
        #: PUMP position (clocks keyed by sid) — a replay tier serves
        #: the same turn-age SLO a live tier does.
        self.freshness = ServerFreshness("replay")
        self.pool = (WriterPool(writer_pool_threads, "gol-replay-writer")
                     if writer_pool_threads > 0 else None)
        self._conn_lock = lockcheck.make_lock("ReplayServer._conn_lock")
        self._conns: "list[_Conn]" = []
        self._by_conn: "dict[_Conn, _Recording]" = {}
        self._replay: "dict[str, dict]" = {}
        self._replay_lock = lockcheck.make_lock("ReplayServer._replay_lock")
        #: Pumps gate on this before their first record — normally
        #: open; `pump_paused=True` holds playback until
        #: `release_pumps()` so an embedder (the bench lane) can
        #: attach a whole observer fleet before a flat-out
        #: (`replay_rate=0`) run starts.
        self._pump_hold = threading.Event()
        if not pump_paused:
            self._pump_hold.set()
        self._shutdown = threading.Event()
        self.done = threading.Event()
        self._threads: "list[threading.Thread]" = []

    # --- lifecycle ---

    def start(self) -> "ReplayServer":
        loops = [(self._accept_loop, "gol-replay-accept")]
        if self.heartbeat_secs > 0:
            loops.append((self._heartbeat_loop, "gol-replay-heartbeat"))
        for fn, name in loops:
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self) -> None:
        if self._shutdown.is_set():
            self.done.wait(timeout=1.0)
            return
        self._shutdown.set()
        with contextlib.suppress(OSError):
            # SHUT_RDWR first (the servers' zombie-accept note).
            self._listener.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self._listener.close()
        with self._conn_lock:
            conns, self._conns = list(self._conns), []
            self._by_conn.clear()
        for rec in self._recordings.values():
            with rec.lock:
                rec.conns = []
        for conn in conns:
            with contextlib.suppress(Exception):
                conn.send({"t": "bye"})
            conn.request_finish()
        deadline = time.monotonic() + self.DRAIN_TIMEOUT
        for conn in conns:
            conn.join_writer(max(0.1, deadline - time.monotonic()))
            conn.close()
        if self.pool is not None:
            self.pool.close()
        self.freshness.close()
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def health(self) -> dict:
        with self._conn_lock:
            peers = len(self._conns)
        return {
            "status": ("shutting-down" if self._shutdown.is_set()
                       else "ok"),
            "role": "replay",
            "recordings": len(self._recordings),
            "peers": peers,
            "turn": max((r.turn for r in self._recordings.values()),
                        default=-1),
            "address": list(self.address),
        }

    # --- accept path (the SessionServer shape, minus the engine) ---

    def _accept_loop(self) -> None:
        from gol_tpu.testing import faults

        while not self._shutdown.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            sock = faults.wrap("server", sock)
            _SRV.accepts.inc()
            try:
                sock.settimeout(self.HELLO_TIMEOUT)
                hello = wire.recv_msg(sock, allow_binary=False)
                if not hello or hello.get("t") != "hello":
                    raise wire.WireError(f"bad hello: {hello!r}")
            except (wire.WireError, OSError, ValueError) as e:
                log.warning("replay rejecting connection from %s: %s",
                            addr, e)
                _SRV.rejects["bad-hello"].inc()
                sock.close()
                continue
            if self._secret is not None and not hmac.compare_digest(
                str(hello.get("secret", "")).encode("utf-8", "replace"),
                self._secret.encode("utf-8", "replace"),
            ):
                log.warning("replay rejecting unauthenticated attach "
                            "from %s", addr)
                _SRV.rejects["unauthorized"].inc()
                with contextlib.suppress(Exception):
                    wire.send_msg(
                        sock, {"t": "error", "reason": "unauthorized"}
                    )
                sock.close()
                continue
            self._admit(sock, hello)

    def _reject(self, sock, reason: str, **extra) -> None:
        with contextlib.suppress(Exception):
            wire.send_msg(sock, {"t": "error", "reason": reason, **extra})
        sock.close()

    def _pick_recording(self, hello: dict) -> "Optional[_Recording]":
        sid = hello.get("session")
        if sid is None:
            if len(self._recordings) == 1:
                return next(iter(self._recordings.values()))
            return None
        return self._recordings.get(sid) if isinstance(sid, str) else None

    def _admit(self, sock: socket.socket, hello: dict) -> None:
        if (self.max_peers is not None
                and len(self._conns) >= self.max_peers):
            _SRV.rejects["at-capacity"].inc()
            self._reject(sock, "at-capacity",
                         retry_after=self.retry_after_secs)
            return
        rec = self._pick_recording(hello)
        if rec is None:
            self._reject(sock, "unknown-session")
            return
        if not hello.get("binary") or not hello.get("want_flips"):
            # Recorded frames are binary FBATCH payloads forwarded
            # verbatim — re-encoding for legacy peers would break the
            # whole tier's invariant (the relay's capability floor).
            self._reject(sock, "replay-binary-only")
            return
        hb = bool(hello.get("hb", False)) and self.heartbeat_secs > 0
        conn = _Conn(sock, True, binary=True, role="observe", hb=hb,
                     batch=_clamp_batch(hello, self.batch_turns),
                     high_water=self.high_water,
                     drain_secs=self.drain_secs, pool=self.pool)
        with self._conn_lock:
            self._conns.append(conn)
            self._by_conn[conn] = rec
            _SRV.peers.set(len(self._conns))
        _SRV.attaches["observe"].inc()
        install_lag_gauge(conn)
        ack = {"t": "attach-ack", "clock": True, "depth": 0,
               "replay": True, "session": rec.sid}
        if conn.batch:
            ack["batch"] = conn.batch
        if hb:
            ack["hb_secs"] = self.heartbeat_secs
        try:
            conn.send(ack)
            conn.start_writer(self._drop_conn)
        except (wire.WireError, OSError):
            self._drop_conn(conn)
            return
        _METRICS.serves.inc()
        tracing.event("replay.attach", "lifecycle", token=conn.token,
                      recording=rec.sid)
        flight.note("replay.attach", token=conn.token, recording=rec.sid)
        # Catch-up + membership in ONE critical section against the
        # pump: the keyframe this peer syncs from and the first
        # broadcast frame it receives are adjacent in the recording.
        with rec.lock:
            self._ensure_pump(rec)
            if rec.catchup:
                try:
                    self._send_catchup(conn, rec.keyframe_turn,
                                       rec.catchup)
                    conn.note_written(rec.turn)
                except (wire.WireError, OSError):
                    self._drop_conn(conn)
                    return
            rec.conns.append(conn)
        threading.Thread(
            target=self._reader_loop, args=(conn,),
            name="gol-replay-reader", daemon=True,
        ).start()

    def _send_catchup(self, conn: _Conn, keyframe_turn: int,
                      payloads: "list[bytes]") -> None:
        """Keyframe + suffix, verbatim bytes (the seek answer shape).
        Control-plane: never shed — it IS the resync."""
        catchup_conn(conn, keyframe_turn, payloads)

    def _drop_conn(self, conn: _Conn) -> None:
        with self._conn_lock:
            removed = conn in self._conns
            if removed:
                self._conns.remove(conn)
            rec = self._by_conn.pop(conn, None)
            _SRV.peers.set(len(self._conns))
        if rec is not None:
            with rec.lock:
                with contextlib.suppress(ValueError):
                    rec.conns.remove(conn)
        if removed:
            _SRV.detaches.inc()
            remove_lag_gauge(conn)
            self.freshness.forget(conn.token)
            tracing.event("replay.detach", "lifecycle", token=conn.token)
        conn.close()

    # --- the pump: one thread per recording, file -> broadcast ---

    def _ensure_pump(self, rec: _Recording) -> None:
        """Start a recording's pump at its FIRST observer (caller
        holds rec.lock) — an unwatched recording costs nothing, not
        even file reads (the static-cache ideal)."""
        if rec.started:
            return
        rec.started = True
        t = threading.Thread(target=self._pump, args=(rec,),
                             name=f"gol-replay-pump-{rec.sid}",
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _pace(self, prev_ts: Optional[float], ts: float,
              turns: int) -> None:
        if self.replay_rate is not None:
            if self.replay_rate > 0 and turns:
                self._shutdown.wait(turns / self.replay_rate)
            return
        if prev_ts is not None and ts > prev_ts:
            self._shutdown.wait(min(ts - prev_ts, PACE_GAP_CAP))

    def release_pumps(self) -> None:
        """Open the playback gate (see `pump_paused`)."""
        self._pump_hold.set()

    def _pump(self, rec: _Recording) -> None:
        while not self._pump_hold.wait(0.1):
            if self._shutdown.is_set():
                return
        prev_ts = None
        for seg_turn, path in scan_segments(rec.root):
            for ts, payload in read_records(path):
                if self._shutdown.is_set():
                    return
                if payload[:1] and payload[0] == wire._TAG_BOARD:
                    self._pace(prev_ts, ts, 0)
                    with rec.lock:
                        rec.catchup = [payload]
                        rec.keyframe_turn = seg_turn
                        rec.turn = max(rec.turn, seg_turn)
                        self.freshness.note_commit(rec.turn, key=rec.sid)
                        for conn in list(rec.conns):
                            if conn.scrub:
                                continue
                            try:
                                self._send_catchup(conn, seg_turn,
                                                   [payload])
                                conn.note_written(rec.turn)
                            except (wire.WireError, OSError):
                                self._drop_conn(conn)
                else:
                    span = fbatch_span(payload)
                    if span is None:
                        continue  # unknown/torn record kinds are skipped
                    first, last = span
                    self._pace(prev_ts, ts, last - first + 1)
                    with rec.lock:
                        rec.catchup.append(payload)
                        if last > rec.turn:
                            _METRICS.turns.inc(last - max(rec.turn,
                                                          first - 1))
                            rec.turn = last
                            self.freshness.note_commit(last, key=rec.sid)
                        self._broadcast(rec, payload, last)
                    _METRICS.position.set(max(
                        r.turn for r in self._recordings.values()
                    ))
                prev_ts = ts
        rec.finished = True
        tracing.event("replay.finished", "lifecycle", recording=rec.sid,
                      turn=rec.turn)
        flight.note("replay.finished", recording=rec.sid, turn=rec.turn)

    def _broadcast(self, rec: _Recording, payload: bytes,
                   last_turn: int) -> None:
        """One recorded stream frame to every attached observer
        (caller holds rec.lock): verbatim bytes, PR 7 shedding per
        peer, drain-recovery via a catch-up resync from the current
        keyframe."""
        for conn in list(rec.conns):
            if conn.lag_metric is not None:
                conn.lag_metric.set(conn.queued())
            if conn.scrub:
                continue  # parked at a seek position
            if conn.drained():
                conn.resync_pending = True
                with contextlib.suppress(wire.WireError, OSError):
                    self._send_catchup(conn, rec.keyframe_turn,
                                       rec.catchup)
                    conn.note_written(rec.turn)
                continue
            if not conn.synced or last_turn <= conn.synced_turn:
                continue
            try:
                if not conn.offer_stream():
                    continue
                conn.send_raw(payload)
                conn.note_written(last_turn)
                _METRICS.frames.inc()
                _METRICS.bytes.inc(len(payload))
            except (wire.WireError, OSError):
                self._drop_conn(conn)

    # --- observer control plane (seek verbs, clk, q) ---

    def _reader_loop(self, conn: _Conn) -> None:
        while True:
            try:
                msg = wire.recv_msg(conn.sock, allow_binary=False)
            except TimeoutError:
                if conn._dead.is_set():
                    self._drop_conn(conn)
                    return
                continue
            except (wire.WireError, OSError):
                msg = None
            if msg is None:
                self._drop_conn(conn)
                return
            conn.last_rx = time.monotonic()
            conn.hb_unanswered = 0
            t = msg.get("t")
            if t == "clk":
                with contextlib.suppress(wire.WireError, OSError):
                    conn.send_direct({"t": "clk", "t0": msg.get("t0"),
                                      "ts": time.time()})
                continue
            if t == "seek":
                self._handle_seek(conn, msg)
                continue
            if t == "key":
                if msg.get("key") == "q":
                    with contextlib.suppress(Exception):
                        conn.send({"t": "detached"})
                    conn.finish()
                    self._drop_conn(conn)
                    return
                with contextlib.suppress(Exception):
                    conn.send({"t": "error", "reason": "replay"})

    def _replay_lookup(self, rid: str) -> Optional[dict]:
        with self._replay_lock:
            return self._replay.get(rid)

    def _replay_record(self, rid: str, reply: dict) -> None:
        with self._replay_lock:
            self._replay[rid] = reply
            while len(self._replay) > self.REPLAY_WINDOW:
                del self._replay[next(iter(self._replay))]

    def _handle_seek(self, conn: _Conn, msg: dict) -> None:
        reply = serve_seek(
            conn, msg, self._by_conn.get(conn),
            replay_lookup=self._replay_lookup,
            replay_record=self._replay_record,
        )
        with contextlib.suppress(wire.WireError, OSError):
            conn.send(reply)

    # --- liveness (the relay's downstream discipline) ---

    def _heartbeat_loop(self) -> None:
        interval = max(0.05, self.heartbeat_secs / 2.0)
        while not self._shutdown.wait(interval):
            now = time.monotonic()
            with self._conn_lock:
                conns = list(self._conns)
                recs = dict(self._by_conn)
            self.freshness.sample(
                (c, recs[c].sid) for c in conns if c in recs
            )
            for conn in conns:
                if not conn.writer_started:
                    continue
                if conn.degraded:
                    if (now - conn.degraded_since > conn.drain_secs
                            and conn.queued() > conn.LOW_WATER):
                        log.warning(
                            "evicting replay peer %d: wedged %.1fs "
                            "past the drain deadline", conn.token,
                            now - conn.degraded_since,
                        )
                        if conn.count_overflow():
                            _SRV.overflows.inc()
                        self._drop_conn(conn)
                    continue
                if (conn.hb and conn.hb_unanswered >= self.HB_MISS_LIMIT
                        and now - conn.last_rx > self.evict_secs):
                    log.warning("evicting unresponsive replay peer %d",
                                conn.token)
                    _SRV.evicted.inc()
                    self._drop_conn(conn)
                    continue
                if now - conn.last_tx >= self.heartbeat_secs:
                    rec = self._by_conn.get(conn)
                    turn = rec.turn if rec is not None else 0
                    try:
                        conn.send_raw(wire.heartbeat_to_frame(max(turn, 0)))
                    except (wire.WireError, OSError):
                        self._drop_conn(conn)
                        continue
                    _SRV.heartbeats.inc()
                    if conn.hb:
                        conn.hb_unanswered += 1


def catchup_conn(conn, keyframe_turn: int,
                 payloads: "list[bytes]") -> None:
    """The ONE resync-from-recorded-bytes sequence (attach catch-up,
    drain recovery, seek serving, live rejoin all share it): forward
    the keyframe + suffix verbatim, then reset the peer's stream
    state so gating and the delta chain restart at the keyframe."""
    for payload in payloads:
        conn.send_raw(payload)
        _METRICS.frames.inc()
        _METRICS.bytes.inc(len(payload))
    conn.synced = True
    conn.synced_turn = keyframe_turn
    conn.delta_prev = None
    conn.mark_recovered()


def valid_seek_turn(turn) -> bool:
    """A seek's "turn" operand: a non-negative plausible int (bools —
    JSON true/false — are ints to Python and are hostile here) or the
    literal "live". Everything else is a reasoned 'bad-turn'."""
    if turn == "live":
        return True
    return (isinstance(turn, int) and not isinstance(turn, bool)
            and 0 <= turn < (1 << 62))


def serve_seek(conn, msg: dict, target,
               replay_lookup=None, replay_record=None) -> dict:
    """The ONE seek implementation both serving planes share (the
    SessionServer passes a recording log dir + live-resync callback,
    the ReplayServer its _Recording): validate the verb, rid-replay a
    completed one verbatim, serve the nearest <= T keyframe's BoardSync
    plus the FBATCH suffix through `conn` (raw bytes, the ordinary
    client apply path), park the peer (`conn.scrub`) until a
    {"turn":"live"} rejoin. Returns the reply dict (ok/reason/turn/
    keyframe), which the caller sends AFTER the frames — the reply is
    the completion marker.

    `target` duck-types: `.root` (log dir), `.lock` (orders the served
    frames against the live/broadcast stream), and optionally
    `.catchup`/`.keyframe_turn`/`.turn` (broadcast position, for
    "live" rejoins) or `.resync_live(conn)` (the session plane's
    engine-thread resync)."""
    rid = msg.get("rid")
    if not (isinstance(rid, str) and 0 < len(rid) <= 128):
        rid = None
    if rid is not None and replay_lookup is not None:
        cached = replay_lookup(rid)
        if cached is not None:
            return cached
    reply = {"t": "seek-r", "ok": False}
    if rid is not None:
        reply["rid"] = rid
    turn = msg.get("turn")
    if not valid_seek_turn(turn):
        reply["reason"] = "bad-turn"
        return reply
    if target is None:
        reply["reason"] = "not-recorded"
        return reply
    if not conn.binary:
        reply["reason"] = "binary-only"
        return reply
    _METRICS.seeks.inc()
    if turn == "live":
        try:
            if hasattr(target, "resync_live"):
                # Session plane: the fresh BoardSync must come from
                # the engine thread, post-commit (the drain-resync
                # ordering) — scrub clears THERE, atomically with the
                # sync, so no live chunk can slip in between.
                target.resync_live(conn)
                reply.update(ok=True, turn=conn.synced_turn)
                return _record(reply, rid, replay_record)
            with target.lock:
                # Broadcast plane (replay server): rejoin from the
                # current segment's keyframe, verbatim bytes.
                conn.scrub = False
                catchup_conn(conn, target.keyframe_turn, target.catchup)
                reply.update(ok=True, turn=target.turn,
                             keyframe=target.keyframe_turn)
            return _record(reply, rid, replay_record)
        except (wire.WireError, OSError):
            raise
        except ValueError as e:
            # SessionError from a live resync (parked/destroyed in
            # between): its message is the wire reason.
            reply["reason"] = str(e) or "unavailable"
            return reply
        except Exception:
            reply["reason"] = "io-error"
            return reply
    try:
        got = seek_frames(target.root, int(turn))
    except OSError:
        got = None
    if got is None:
        reply["reason"] = "not-recorded"
        return reply
    keyframe, landed, payloads = got
    with target.lock:
        # Park FIRST, then serve: once scrub is visible under the
        # lock, no live/broadcast frame can interleave after our
        # BoardSync (which would XOR garbage onto the seeked board).
        conn.scrub = True
        catchup_conn(conn, keyframe, payloads)
    tracing.event("replay.seek", "wire", turn=turn, keyframe=keyframe,
                  landed=landed)
    reply.update(ok=True, turn=landed, keyframe=keyframe)
    return _record(reply, rid, replay_record)


def _record(reply: dict, rid, replay_record) -> dict:
    if rid is not None and replay_record is not None and reply.get("ok"):
        replay_record(rid, reply)
    return reply
