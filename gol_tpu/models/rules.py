"""Cellular-automaton rule models.

The reference hard-codes Conway's B3/S23 in two places (worker path ref:
gol/distributor.go:325-342, serial path ref: gol/distributor.go:350-379).
Here the rule is a *model*: a (birth, survival) pair over the
8-neighbour count in standard B/S notation. The step kernel unrolls the
sets into fused compare/or terms at trace time (ops/life.py:apply_rule),
so Conway Life costs exactly the same as any other life-like rule and no
lookup happens at runtime.
"""

from __future__ import annotations

import dataclasses
import re

_RULE_RE = re.compile(r"^B(?P<birth>[0-8]*)/S(?P<survive>[0-8]*)$", re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class Rule:
    """A life-like rule: dead cell with n neighbours becomes alive iff
    n ∈ birth; live cell stays alive iff n ∈ survive (B3/S23 semantics
    ref: gol/distributor.go:325-342)."""

    name: str
    birth: frozenset
    survive: frozenset

    @classmethod
    def parse(cls, notation: str) -> "Rule":
        m = _RULE_RE.match(notation.strip())
        if not m:
            raise ValueError(f"bad B/S rule notation: {notation!r}")
        return cls(
            name=notation.upper(),
            birth=frozenset(int(c) for c in m.group("birth")),
            survive=frozenset(int(c) for c in m.group("survive")),
        )

    def __str__(self) -> str:
        return self.name


LIFE = Rule.parse("B3/S23")


_GEN_RULE_RE = re.compile(
    r"^B(?P<birth>[0-8]*)/S(?P<survive>[0-8]*)/C(?P<states>\d+)$",
    re.IGNORECASE,
)


@dataclasses.dataclass(frozen=True)
class GenRule:
    """A Generations rule — the multi-state extension of the life-like
    family (B/S/C notation): cell states are 0 (dead), 1 (alive),
    2..states-1 (dying). An alive cell with n ∈ survive stays alive,
    else starts dying; a dead cell with n ∈ birth is born; a dying cell
    ages by one each turn until it wraps to dead. Only state-1 cells
    count as neighbours. C=2 has no dying states and reduces exactly to
    the life-like `Rule` with the same B/S sets (asserted in tests).

    No reference analog — the reference hard-codes two-state B3/S23;
    this is the `models/` axis generalized one step further (classic
    members: Brian's Brain B2/S/C3, Star Wars B2/S345/C4)."""

    name: str
    birth: frozenset
    survive: frozenset
    states: int

    @classmethod
    def parse(cls, notation: str) -> "GenRule":
        m = _GEN_RULE_RE.match(notation.strip())
        if not m:
            raise ValueError(f"bad B/S/C generations notation: {notation!r}")
        states = int(m.group("states"))
        if not 2 <= states <= 255:
            # Above 255 the uint8 state grid overflows and the gray-
            # level PGM mapping loses injectivity (ops/generations.py).
            raise ValueError(
                f"generations rule needs 2 <= states <= 255: {notation!r}"
            )
        return cls(
            name=notation.upper(),
            birth=frozenset(int(c) for c in m.group("birth")),
            survive=frozenset(int(c) for c in m.group("survive")),
            states=states,
        )

    def __str__(self) -> str:
        return self.name


#: A few well-known model variants, usable via Params(rule=...).
RULES = {
    "B3/S23": LIFE,  # Conway's Game of Life — the reference's model
    "B36/S23": Rule.parse("B36/S23"),  # HighLife
    "B3678/S34678": Rule.parse("B3678/S34678"),  # Day & Night
    "B1357/S1357": Rule.parse("B1357/S1357"),  # Replicator
    "B2/S": Rule.parse("B2/S"),  # Seeds
    "B2/S/C3": GenRule.parse("B2/S/C3"),  # Brian's Brain
    "B2/S345/C4": GenRule.parse("B2/S345/C4"),  # Star Wars
}


def get_rule(notation: str):
    """Resolve B/S (life-like `Rule`) or B/S/C (`GenRule`) notation."""
    notation = notation.strip()  # both parsers strip; the named lookup
    # must too, or ' B3/S23 ' would return a fresh non-identical Rule
    named = RULES.get(notation.upper())
    if named is not None:
        return named
    if _GEN_RULE_RE.match(notation.strip()):
        return GenRule.parse(notation)
    return Rule.parse(notation)
