"""Cellular-automaton rule models.

The reference hard-codes Conway's B3/S23 in two places (worker path ref:
gol/distributor.go:325-342, serial path ref: gol/distributor.go:350-379).
Here the rule is a *model*: a (birth, survival) pair over the
8-neighbour count in standard B/S notation. The step kernel unrolls the
sets into fused compare/or terms at trace time (ops/life.py:apply_rule),
so Conway Life costs exactly the same as any other life-like rule and no
lookup happens at runtime.
"""

from __future__ import annotations

import dataclasses
import re

_RULE_RE = re.compile(r"^B(?P<birth>[0-8]*)/S(?P<survive>[0-8]*)$", re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class Rule:
    """A life-like rule: dead cell with n neighbours becomes alive iff
    n ∈ birth; live cell stays alive iff n ∈ survive (B3/S23 semantics
    ref: gol/distributor.go:325-342)."""

    name: str
    birth: frozenset
    survive: frozenset

    @classmethod
    def parse(cls, notation: str) -> "Rule":
        m = _RULE_RE.match(notation.strip())
        if not m:
            raise ValueError(f"bad B/S rule notation: {notation!r}")
        return cls(
            name=notation.upper(),
            birth=frozenset(int(c) for c in m.group("birth")),
            survive=frozenset(int(c) for c in m.group("survive")),
        )

    def __str__(self) -> str:
        return self.name


LIFE = Rule.parse("B3/S23")

#: A few well-known life-like model variants, usable via Params(rule=...).
RULES = {
    "B3/S23": LIFE,  # Conway's Game of Life — the reference's model
    "B36/S23": Rule.parse("B36/S23"),  # HighLife
    "B3678/S34678": Rule.parse("B3678/S34678"),  # Day & Night
    "B1357/S1357": Rule.parse("B1357/S1357"),  # Replicator
    "B2/S": Rule.parse("B2/S"),  # Seeds
}


def get_rule(notation: str) -> Rule:
    return RULES.get(notation.upper()) or Rule.parse(notation)
