from gol_tpu.models.rules import Rule, LIFE, RULES

__all__ = ["Rule", "LIFE", "RULES"]
