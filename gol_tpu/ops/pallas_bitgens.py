"""Pallas TPU kernel for packed Generations — one-hot planes in VMEM.

The XLA packed-gens loop (`ops/bitgens.py`) bounces the plane stack
through HBM every turn; this kernel keeps all C-1 one-hot planes
VMEM-resident for the whole multi-turn chunk, exactly as
`ops/pallas_bitlife.py` does for the two-state board. Planes are
separate 2-D refs (Mosaic-friendly), the turn body is the shared
`bitgens.step_planes` with `pltpu.roll` primitives, and the loop uses
the same UNROLL discipline as the life kernels.

Whole-board only: a generations run that outgrows VMEM falls back to
the XLA path (the strip-tiled construction would apply identically if
ever needed — the light-cone argument is rule-independent)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.models.rules import GenRule
from gol_tpu.ops import bitgens
from gol_tpu.ops.bitlife import WORD
from gol_tpu.ops.pallas_bitlife import UNROLL, VMEM_BUDGET_BYTES


def fits_pallas_gens(height: int, width: int, rule: GenRule) -> bool:
    """Working set within the VMEM budget, with the same tile-alignment
    gates as the two-state kernel. The kernel holds C-1 *input* refs
    and C-1 *output* refs simultaneously (pallas_call does not alias
    them) plus ~8 live CSA temporaries — the life model's 10x factor
    (1 in + 1 out + 8 temps) generalizes to 2*(C-1) + 8 plane
    equivalents, agreeing with it at C=2."""
    if height % WORD != 0:
        return False
    rows = height // WORD
    if rows % 8 != 0 or width % 128 != 0:
        return False
    working = rows * width * 4 * (2 * (rule.states - 1) + 8)
    return working <= VMEM_BUDGET_BYTES


def _gens_turn(planes: tuple, rule: GenRule) -> tuple:
    alive = planes[0]
    one, top = 1, WORD - 1
    rows = alive.shape[0]
    up = (alive << one) | (pltpu.roll(alive, 1, 0) >> top)
    down = (alive >> one) | (pltpu.roll(alive, rows - 1, 0) << top)
    return bitgens.step_planes(planes, rule, up, down, roll=pltpu.roll)


def _make_kernel(n_turns: int, rule: GenRule):
    nplanes = rule.states - 1

    def body(_, planes):
        for _ in range(UNROLL):
            planes = _gens_turn(planes, rule)
        return planes

    def kernel(*refs):
        planes = tuple(r[:] for r in refs[:nplanes])
        whole, rem = divmod(n_turns, UNROLL)
        if whole:
            planes = lax.fori_loop(0, whole, body, planes)
        for _ in range(rem):
            planes = _gens_turn(planes, rule)
        for out_ref, plane in zip(refs[nplanes:], planes):
            out_ref[:] = plane

    return kernel


@functools.partial(jax.jit, static_argnames=("n", "rule", "interpret"))
def step_n_packed_gens_pallas_raw(
    planes: jax.Array,
    n: int,
    rule: GenRule,
    interpret: bool = False,
) -> jax.Array:
    """`n` turns on stacked (C-1, rows, W) planes, one kernel call —
    drop-in for `bitgens.step_n_packed_gens_raw` when
    `fits_pallas_gens`."""
    nplanes = rule.states - 1
    shape = jax.ShapeDtypeStruct(planes.shape[1:], jnp.uint32)
    outs = pl.pallas_call(
        _make_kernel(n, rule),
        out_shape=[shape] * nplanes,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * nplanes,
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * nplanes,
        interpret=interpret,
    )(*(planes[i] for i in range(nplanes)))
    return jnp.stack(outs)
