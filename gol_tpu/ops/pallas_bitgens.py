"""Pallas TPU kernels for packed Generations — one-hot planes in VMEM.

The XLA packed-gens loop (`ops/bitgens.py`) bounces the plane stack
through HBM every turn; these kernels keep all C-1 one-hot planes
VMEM-resident across multi-turn chunks, exactly as
`ops/pallas_bitlife.py` does for the two-state board. Planes are
separate 2-D refs (Mosaic-friendly), the turn body is the shared
`bitgens.step_planes` with `pltpu.roll` primitives, and the loop uses
the same UNROLL discipline as the life kernels.

Two forms, mirroring the life kernels:

- whole-board: every plane resident for the full chunk;
- strip-tiled with deep halos: boards over the VMEM budget run as
  row strips advancing 32·h turns per HBM pass. EVERY plane carries
  the h-word ghost slab — the stencil itself only reads the alive
  plane, but dead-ness (birth eligibility) reads all planes, so the
  ghost rows of every plane feed the light cone. Validity shrinks one
  bit-row per turn exactly as in the two-state argument.

A per-plane working set that beats both falls back to the XLA path."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.models.rules import GenRule
from gol_tpu.ops import bitgens
from gol_tpu.ops.bitlife import WORD
from gol_tpu.ops.pallas_bitlife import TILE_TURNS, UNROLL, VMEM_BUDGET_BYTES


def fits_pallas_gens(height: int, width: int, rule: GenRule) -> bool:
    """Working set within the VMEM budget, with the same tile-alignment
    gates as the two-state kernel (cost model: _plane_equivalents)."""
    if height % WORD != 0:
        return False
    rows = height // WORD
    if rows % 8 != 0 or width % 128 != 0:
        return False
    working = rows * width * 4 * _plane_equivalents(rule)
    return working <= VMEM_BUDGET_BYTES


def _plane_equivalents(rule: GenRule) -> int:
    """Whole-board VMEM cost in board-sized arrays: the kernel holds
    C-1 *input* refs and C-1 *output* refs simultaneously (pallas_call
    does not alias them) plus ~8 live CSA temporaries — the life
    model's 10x factor (1 in + 1 out + 8 temps) generalized,
    agreeing with it at C=2."""
    return 2 * (rule.states - 1) + 8


def _gens_turn(planes: tuple, rule: GenRule) -> tuple:
    alive = planes[0]
    one, top = 1, WORD - 1
    rows = alive.shape[0]
    up = (alive << one) | (pltpu.roll(alive, 1, 0) >> top)
    down = (alive >> one) | (pltpu.roll(alive, rows - 1, 0) << top)
    return bitgens.step_planes(planes, rule, up, down, roll=pltpu.roll)


def _gens_split_turn(slices: list, rule: GenRule) -> list:
    """One exact toroidal turn on k row-slices of the plane stack —
    the gens twin of pallas_bitlife._split_turn: only the ALIVE plane
    carries across slice seams (a gens cell's update needs
    alive-neighbour counts only), every plane is sliced alike.
    Measured +12.5% at 1024² C3 (drift-cancelled medians), mirroring
    the Life kernel's interleave win."""
    one, top = 1, WORD - 1
    k = len(slices)
    out = []
    for i, planes in enumerate(slices):
        alive = planes[0]
        cu = jnp.concatenate(
            [slices[(i - 1) % k][0][-1:], alive[:-1]], axis=0
        )
        cd = jnp.concatenate(
            [alive[1:], slices[(i + 1) % k][0][:1]], axis=0
        )
        up = (alive << one) | (cu >> top)
        down = (alive >> one) | (cd << top)
        out.append(bitgens.step_planes(planes, rule, up, down,
                                       roll=pltpu.roll))
    return out


def _run_gens_turns(planes: tuple, n_turns: int, rule: GenRule,
                    interleave: bool = False) -> tuple:
    """`n_turns` in-kernel turns on a plane tuple: an UNROLL-deep loop
    plus remainder — the gens mirror of pallas_bitlife._run_turns,
    including the whole-board slice interleave (sublane-aligned k via
    the SAME _interleave_k policy; tiled callers keep the single
    chain)."""
    from gol_tpu.ops.pallas_bitlife import _interleave_k

    k = _interleave_k(planes[0].shape[0]) if interleave else 1
    if k == 1:
        def body(_, pl_):
            for _ in range(UNROLL):
                pl_ = _gens_turn(pl_, rule)
            return pl_

        whole, rem = divmod(n_turns, UNROLL)
        if whole:
            planes = lax.fori_loop(0, whole, body, planes)
        for _ in range(rem):
            planes = _gens_turn(planes, rule)
        return planes

    rows = planes[0].shape[0]
    slices = tuple(
        tuple(p[i * rows // k : (i + 1) * rows // k] for p in planes)
        for i in range(k)
    )

    def body(_, ss):
        for _ in range(UNROLL):
            ss = tuple(_gens_split_turn(ss, rule))
        return ss

    whole, rem = divmod(n_turns, UNROLL)
    if whole:
        slices = lax.fori_loop(0, whole, body, slices)
    for _ in range(rem):
        slices = tuple(_gens_split_turn(slices, rule))
    return tuple(
        jnp.concatenate([s[j] for s in slices], axis=0)
        for j in range(len(planes))
    )


def _make_kernel(n_turns: int, rule: GenRule):
    nplanes = rule.states - 1

    def kernel(*refs):
        planes = tuple(r[:] for r in refs[:nplanes])
        planes = _run_gens_turns(planes, n_turns, rule, interleave=True)
        for out_ref, plane in zip(refs[nplanes:], planes):
            out_ref[:] = plane

    return kernel


@functools.partial(jax.jit, static_argnames=("n", "rule", "interpret"))
def step_n_packed_gens_pallas_raw(
    planes: jax.Array,
    n: int,
    rule: GenRule,
    interpret: bool = False,
) -> jax.Array:
    """`n` turns on stacked (C-1, rows, W) planes, one kernel call —
    drop-in for `bitgens.step_n_packed_gens_raw` when
    `fits_pallas_gens`."""
    nplanes = rule.states - 1
    shape = jax.ShapeDtypeStruct(planes.shape[1:], jnp.uint32)
    outs = pl.pallas_call(
        _make_kernel(n, rule),
        out_shape=[shape] * nplanes,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * nplanes,
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * nplanes,
        interpret=interpret,
    )(*(planes[i] for i in range(nplanes)))
    return jnp.stack(outs)


# --- strip-tiled form (boards over the whole-board VMEM budget) ---


def _tiled_plane_equivalents(rule: GenRule) -> int:
    """Tiled VMEM cost in ext-strip-sized arrays. The grid pipeline
    DOUBLE-buffers every plane's in blocks and out strip on top of the
    kernel's live temporaries (same effect the life kernel pins with
    STRIP_ROWS_CAP): ~3 strip-sized buffers per plane + the CSA
    temporaries. Empirically: the 2(C-1)+8 model admitted an 8192² C=3
    config that compiled to 17.35 MB scoped vs the 16 MB limit; this
    model rejects it and its accepted configs compile clean."""
    return 3 * (rule.states - 1) + 9


def _gens_tile_plan(rows: int, width: int, rule: GenRule,
                    strip_rows: int | None,
                    halo_words: int | None) -> tuple:
    """(strip height, halo depth) for the tiled gens kernel — the
    shared tiling policy (pallas_bitlife._tile_plan) with the
    plane-count-scaled per-row cost."""
    from gol_tpu.ops.pallas_bitlife import _tile_plan

    return _tile_plan(
        rows, width, strip_rows, halo_words,
        row_cost=width * 4 * _tiled_plane_equivalents(rule),
    )


def fits_pallas_gens_tiled(height: int, width: int, rule: GenRule) -> bool:
    """Tiled eligibility: tile-aligned packed shape and a minimum
    8-row strip (plus halos) within the plane-scaled budget."""
    if height % WORD != 0:
        return False
    rows = height // WORD
    if rows % 8 != 0 or width % 128 != 0:
        return False
    return 10 * width * 4 * _tiled_plane_equivalents(rule) <= VMEM_BUDGET_BYTES


def _make_tiled_kernel(k_turns: int, rule: GenRule, halo: int):
    assert 1 <= k_turns <= TILE_TURNS * halo
    nplanes = rule.states - 1

    def kernel(*refs):
        # Per plane: up halo block, centre strip, down halo block —
        # grouped per plane in the in_specs order below.
        ext = tuple(
            jnp.concatenate(
                [refs[3 * i][8 - halo:], refs[3 * i + 1][:],
                 refs[3 * i + 2][:halo]],
                axis=0,
            )
            for i in range(nplanes)
        )
        ext = _run_gens_turns(ext, k_turns, rule)
        for i in range(nplanes):
            refs[3 * nplanes + i][:] = ext[i][halo:-halo]

    return kernel


def _tiled_call(planes: jax.Array, k_turns: int, rule: GenRule,
                interpret: bool, r: int, h: int):
    nplanes, rows, width = planes.shape
    nstrips = rows // r
    blocks = r // 8
    in_specs = []
    args = []
    for i in range(nplanes):
        in_specs += [
            pl.BlockSpec(
                (8, width),
                lambda j: (((j - 1) % nstrips) * blocks + blocks - 1, 0),
            ),
            pl.BlockSpec((r, width), lambda j: (j, 0)),
            pl.BlockSpec((8, width), lambda j: (((j + 1) % nstrips) * blocks, 0)),
        ]
        args += [planes[i]] * 3
    out_spec = pl.BlockSpec((r, width), lambda j: (j, 0))
    shape = jax.ShapeDtypeStruct((rows, width), jnp.uint32)
    outs = pl.pallas_call(
        _make_tiled_kernel(k_turns, rule, h),
        grid=(nstrips,),
        in_specs=in_specs,
        out_specs=[out_spec] * nplanes,
        out_shape=[shape] * nplanes,
        interpret=interpret,
    )(*args)
    return jnp.stack(outs)


@functools.partial(
    jax.jit,
    static_argnames=("n", "rule", "interpret", "strip_rows", "halo_words"),
)
def step_n_packed_gens_pallas_tiled_raw(
    planes: jax.Array,
    n: int,
    rule: GenRule,
    interpret: bool = False,
    strip_rows: int | None = None,
    halo_words: int | None = None,
) -> jax.Array:
    """`n` turns on stacked (C-1, rows, W) planes, strip-tiled with
    h-word ghost slabs on EVERY plane — 32·h turns per HBM pass for
    boards too big for the whole-board kernel. `strip_rows`/
    `halo_words` override the auto sizing (tests force multi-strip
    seams and light-cone boundaries on small boards)."""
    _, rows, width = planes.shape
    r, h = _gens_tile_plan(rows, width, rule, strip_rows, halo_words)
    k = TILE_TURNS * h
    whole, rem = divmod(n, k)
    if whole:
        planes = lax.fori_loop(
            0, whole,
            lambda _, q: _tiled_call(q, k, rule, interpret, r, h),
            planes,
        )
    if rem:
        h_rem = min(h, -(-rem // TILE_TURNS))
        planes = _tiled_call(planes, rem, rule, interpret, r, h_rem)
    return planes

# --- 2-D tiled form (very wide boards) -------------------------------------
#
# The 1-D gens strips are even thinner than Life's — the per-row VMEM
# cost scales with the plane count — so wide gens boards hit the same
# thin-strip dependency-chain wall (docs/PERF.md, the 512² study).
# This is pallas_bitlife's 2-D tiled kernel applied per plane: every
# plane contributes a full 9-view ghost frame (vertical bands, narrow
# horizontal edge blocks, corner blocks from the diagonal tiles), and
# the tile width adapts to the plane count so the tile height stays at
# the fast >=32-word-row shape where the budget allows.


def _gens_tile2d_plan(rows: int, width: int, rule: GenRule,
                      tile_rows: int | None = None):
    """(tile height r, halo h, tile width wt) for a 2-D gens tiling, or
    None when no width tile fits. Prefers the TALLEST tile (op shape
    dominates: r=64 at half width measured over r=32 at full width,
    2.27 vs 2.17 Tcells/s at 8192² C=3), width as the tie-break."""
    from gol_tpu.ops.pallas_bitlife import (
        TILE2D_GHOST_LANES,
        TILE2D_WIDTH,
        _halo_words,
        _strip_rows,
    )

    mult = _tiled_plane_equivalents(rule)
    plans = []
    for wt in (TILE2D_WIDTH, TILE2D_WIDTH // 2):
        if width % wt != 0 or width <= wt:
            continue
        extw = wt + 2 * TILE2D_GHOST_LANES
        cost = extw * 4 * mult
        if 10 * cost > VMEM_BUDGET_BYTES:  # minimum 8+2 rows must fit
            continue
        r = tile_rows or _strip_rows(rows, extw, cost)
        h = _halo_words(r, extw, cost)
        plans.append((r, h, wt))
    if not plans:
        return None
    return max(plans, key=lambda p: (p[0], p[2]))


def fits_pallas_gens_tiled2d(height: int, width: int,
                             rule: GenRule) -> bool:
    if height % WORD != 0:
        return False
    rows = height // WORD
    if rows % 8 != 0 or width % 128 != 0 or rows < 8:
        return False
    return _gens_tile2d_plan(rows, width, rule) is not None


def prefer_gens_tiled2d(height: int, width: int, rule: GenRule) -> bool:
    """True when the 2-D tiling's tile height genuinely beats the 1-D
    strip plan's. The 2-D frame pays ghost-column compute and corner
    fetches for its taller tiles, so equal heights favour 1-D — e.g. a
    C=2 rule at 4096² reaches r=64 full-width strips and must keep
    them."""
    if not fits_pallas_gens_tiled2d(height, width, rule):
        return False
    rows = height // WORD
    r2d = _gens_tile2d_plan(rows, width, rule)[0]
    if not fits_pallas_gens_tiled(height, width, rule):
        return True
    r1d = _gens_tile_plan(rows, width, rule, None, None)[0]
    return r2d > r1d


def _make_tiled2d_kernel(k_turns: int, rule: GenRule, halo: int, hw: int):
    from gol_tpu.ops.pallas_bitlife import MAX_HALO_WORDS

    assert 1 <= k_turns <= min(TILE_TURNS * halo, hw)
    assert 1 <= halo <= MAX_HALO_WORDS
    nplanes = rule.states - 1

    def kernel(*refs):
        ext = []
        for i in range(nplanes):
            ul, ub, ur, le, c, ri, dl, db, dr = refs[9 * i : 9 * i + 9]
            top = jnp.concatenate(
                [ul[8 - halo:, -hw:], ub[8 - halo:, :], ur[8 - halo:, :hw]],
                axis=1,
            )
            mid = jnp.concatenate([le[:, -hw:], c[:], ri[:, :hw]], axis=1)
            bot = jnp.concatenate(
                [dl[:halo, -hw:], db[:halo, :], dr[:halo, :hw]], axis=1
            )
            ext.append(jnp.concatenate([top, mid, bot], axis=0))
        ext = _run_gens_turns(tuple(ext), k_turns, rule)
        for i in range(nplanes):
            refs[9 * nplanes + i][:] = ext[i][halo:-halo, hw:-hw]

    return kernel


def _gens_tiled2d_call(planes: jax.Array, k_turns: int, rule: GenRule,
                       interpret: bool, r: int, h: int, wt: int):
    from gol_tpu.ops.pallas_bitlife import TILE2D_GHOST_LANES, tiled2d_specs

    nplanes, rows, width = planes.shape
    frame = tiled2d_specs(rows, width, r, wt)
    centre = frame[4]
    in_specs, args = [], []
    for i in range(nplanes):
        in_specs += list(frame)
        args += [planes[i]] * 9
    shape = jax.ShapeDtypeStruct((rows, width), jnp.uint32)
    outs = pl.pallas_call(
        _make_tiled2d_kernel(k_turns, rule, h, TILE2D_GHOST_LANES),
        grid=(rows // r, width // wt),
        in_specs=in_specs,
        out_specs=[centre] * nplanes,
        out_shape=[shape] * nplanes,
        interpret=interpret,
    )(*args)
    return jnp.stack(outs)


@functools.partial(
    jax.jit, static_argnames=("n", "rule", "interpret", "tile_rows")
)
def step_n_packed_gens_pallas_tiled2d_raw(
    planes: jax.Array,
    n: int,
    rule: GenRule,
    interpret: bool = False,
    tile_rows: int | None = None,
) -> jax.Array:
    """`n` turns on stacked (C-1, rows, W) planes, tiled in BOTH
    dimensions — the wide-board gens path (see the section comment).
    `tile_rows` overrides the auto height (tests force multi-tile
    seams on small boards)."""
    from gol_tpu.ops.pallas_bitlife import TILE2D_GHOST_LANES

    _, rows, width = planes.shape
    plan = _gens_tile2d_plan(rows, width, rule, tile_rows)
    if plan is None:
        raise ValueError(f"no 2-D gens tiling fits {rows}x{width} C={rule.states}")
    r, h, wt = plan
    if rows % r != 0 or r % 8 != 0:
        raise ValueError(f"tile_rows={r} must divide {rows} in 8-row units")
    k = min(TILE_TURNS * h, TILE2D_GHOST_LANES)
    whole, rem = divmod(n, k)
    if whole:
        planes = lax.fori_loop(
            0, whole,
            lambda _, q: _gens_tiled2d_call(q, k, rule, interpret, r, h, wt),
            planes,
        )
    if rem:
        h_rem = min(h, -(-rem // TILE_TURNS))
        planes = _gens_tiled2d_call(planes, rem, rule, interpret, r, h_rem, wt)
    return planes
