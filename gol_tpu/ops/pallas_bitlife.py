"""Pallas TPU kernel over the bit-packed board — packed SWAR x VMEM-resident.

`ops/pallas_life.py` keeps a dense board in VMEM; `ops/bitlife.py` packs
32 cells per uint32 word but runs under XLA's `fori_loop`, whose
loop-carried buffer lives in HBM. This kernel combines both wins: the
*packed* board (32x smaller) stays resident in VMEM for the entire
K-turn chunk — one HBM round trip per chunk, ~35 VPU bitwise ops per
32-cell word per turn (rule masks minimized by `ops/rulecomp.py`),
zero relayouts between turns.

Same layout and stencil as `ops/bitlife.py` (`packed[r, x]` holds rows
`32r..32r+31` of column `x`); vertical toroidal shifts are word
bit-shifts with cross-word carries fetched by `pltpu.roll` on the
sublane axis, horizontal shifts are `pltpu.roll` on the lane axis. The
CSA count tree and rule minterm masks are imported from `bitlife` —
one definition of the packed rule engine's arithmetic.

Bit-exactness vs the XLA packed path is asserted in tests (interpreter
mode on CPU + golden boards). Serial-sweep analog of
ref: gol/distributor.go:350-379, done as a resident-VMEM packed kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.models.rules import LIFE, Rule
from gol_tpu.ops.bitlife import WORD, combine_packed, pack, unpack
from gol_tpu.ops.life import from_bits, to_bits

#: Working-set budget for board + live CSA temporaries, as
#: rows*width*4 bytes x the 10x live-array multiplier. The hard scoped-
#: VMEM limit on this TPU generation is 16 MB (a 19.8 MB request fails
#: with "exceeded scoped vmem limit 16.00M") and Mosaic keeps ~8.5
#: word-arrays live at the kernel's peak, so a 15 MB model budget
#: (~12.7 MB real) leaves headroom; configs at the model's edge run
#: clean on hardware. One constant for the whole-board and tiled
#: kernels and the sharded ring's local blocks — the same kernel body
#: must not be admitted by one gate and rejected by another.
VMEM_BUDGET_BYTES = 15 << 20


def fits_pallas_packed(height: int, width: int) -> bool:
    """Whole-packed-board-in-VMEM eligibility: whole 32-row words, TPU
    tile-aligned packed shape (sublanes % 8, lanes % 128), and the
    working set within budget."""
    if height % WORD != 0:
        return False
    rows = height // WORD
    if rows % 8 != 0 or width % 128 != 0:
        return False
    return rows * width * 4 * 10 <= VMEM_BUDGET_BYTES


def _pallas_turn(p: jax.Array, rule: Rule) -> jax.Array:
    """One packed turn inside a kernel: vertical toroidal shifts via
    sublane rolls + cross-word carry bits, then the shared column-sum
    rule combine with `pltpu.roll` as the lane-roll primitive. Shifts
    use plain ints (not traced uint32 scalars) so the kernel body closes
    over no constants — pallas requires a closed jaxpr."""
    one, top = 1, WORD - 1
    rows = p.shape[0]
    up = (p << one) | (pltpu.roll(p, 1, 0) >> top)
    down = (p >> one) | (pltpu.roll(p, rows - 1, 0) << top)
    return combine_packed(p, up, down, rule, roll=pltpu.roll)


#: Turns per loop iteration inside the kernels. Mosaic lowers
#: `fori_loop` to a scalar-core loop whose per-iteration overhead is
#: visible on small boards (a packed 512² board is only 8 vregs of
#: vector work per turn); hand-unrolling 8 turns per iteration buys
#: ~5-8% at 512² and is neutral on large boards. Mosaic itself only
#: supports unroll=1 or full unroll, hence the nested form.
UNROLL = 8


def _turns_body(rule: Rule, unroll: int):
    def body(_, p):
        for _ in range(unroll):
            p = _pallas_turn(p, rule)
        return p

    return body


def _split_turn(parts: list, rule: Rule) -> list:
    """One exact toroidal turn on k row-slices of one board, all k
    updated per call: each slice's cross-word carries come from its
    ring-neighbour slices (concatenated edge word-rows instead of the
    whole-board sublane roll). Bit-identical to `_pallas_turn` on the
    concatenated board; the point is the SCHEDULE — k mostly-
    independent dependency chains interleave on the VPU where one
    chain stalls it (the ilp_study finding, productized: drift-
    cancelled A/Bs measured +13% at 1024² and +23% at 2048² for
    8-row slices; BENCH_DETAIL split_interleave)."""
    one, top = 1, WORD - 1
    k = len(parts)
    out = []
    for i, a in enumerate(parts):
        cu = jnp.concatenate([parts[(i - 1) % k][-1:], a[:-1]], axis=0)
        cd = jnp.concatenate([a[1:], parts[(i + 1) % k][:1]], axis=0)
        up = (a << one) | (cu >> top)
        down = (a >> one) | (cd << top)
        out.append(combine_packed(a, up, down, rule, roll=pltpu.roll))
    return out


def _interleave_k(rows: int) -> int:
    """Slice count for the whole-board kernel's interleaved form:
    8-row slices (the sublane tile) measured best at every size that
    can form at least two of them; capped at 8 (beyond that the
    unrolled body bloats compile with no further measured gain).
    Slices must stay sublane-ALIGNED (a multiple of 8 rows): the
    ghost-extended ring strips are e.g. 40 word-rows, and k=4 there
    would make misaligned 10-row slices — measured 27% BELOW the
    un-interleaved kernel (the r5 capture's ring1_1024 regression);
    such shapes keep the single chain."""
    for k in (8, 4, 2):
        if rows % (8 * k) == 0:  # k slices, each a whole multiple of 8
            return k
    return 1


def _run_turns(p: jax.Array, n_turns: int, rule: Rule,
               interleave: bool = False) -> jax.Array:
    """`n_turns` in-kernel turns: an UNROLL-deep loop plus remainder.
    `interleave` runs the k-way sliced form (see _split_turn) — the
    whole-board kernel's configuration; the tiled kernels keep the
    single chain (their strips stream through the grid pipeline,
    a different scheduling regime)."""
    k = _interleave_k(p.shape[0]) if interleave else 1
    if k == 1:
        whole, rem = divmod(n_turns, UNROLL)
        if whole:
            p = lax.fori_loop(0, whole, _turns_body(rule, UNROLL), p)
        for _ in range(rem):
            p = _pallas_turn(p, rule)
        return p
    rows = p.shape[0]
    parts = tuple(p[i * rows // k : (i + 1) * rows // k] for i in range(k))

    def body(_, ps):
        for _ in range(UNROLL):
            ps = tuple(_split_turn(list(ps), rule))
        return ps

    whole, rem = divmod(n_turns, UNROLL)
    if whole:
        parts = lax.fori_loop(0, whole, body, parts)
    for _ in range(rem):
        parts = tuple(_split_turn(list(parts), rule))
    return jnp.concatenate(parts, axis=0)


def _make_kernel(n_turns: int, rule: Rule):
    def kernel(in_ref, out_ref):
        out_ref[:] = _run_turns(in_ref[:], n_turns, rule, interleave=True)

    return kernel


@functools.partial(jax.jit, static_argnames=("n", "rule", "interpret"))
def step_n_packed_pallas_raw(
    p: jax.Array,
    n: int,
    rule: Rule = LIFE,
    interpret: bool = False,
) -> jax.Array:
    """`n` turns, packed uint32 in / packed uint32 out, one kernel call."""
    return pl.pallas_call(
        _make_kernel(n, rule),
        out_shape=jax.ShapeDtypeStruct(p.shape, jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(p)


#: Hard cap on the tiled kernel's strip height (word rows). The grid
#: pipeline double-buffers the strip-sized in/out blocks *on top of*
#: the kernel's live temporaries, and that sum is what the 16 MB scoped
#: limit sees: a 72-row strip with 4-word halos compiles to a 16.04 MB
#: scoped allocation (fails by 44 KB) while every measured r <= 64
#: config compiles clean — the budget model alone can't separate them
#: across widths, so the knee is pinned empirically.
STRIP_ROWS_CAP = 64


def _strip_rows(total_rows: int, width: int,
                row_cost: int | None = None) -> int:
    """Strip height (word rows) for the tiled kernel: largest divisor of
    `total_rows` that is a multiple of 8, within the working-set budget
    ((R+2) x `row_cost` bytes/row), and under STRIP_ROWS_CAP.
    `row_cost` defaults to the two-state model (width x 4 x ~10 live
    arrays); the generations kernel passes its plane-scaled cost so
    ONE tiling policy serves both (ops/pallas_bitgens.py)."""
    row_cost = row_cost or width * 4 * 10
    budget_rows = min(VMEM_BUDGET_BYTES // row_cost - 2, STRIP_ROWS_CAP)
    r = 8
    for cand in range(8, total_rows + 1, 8):
        if total_rows % cand == 0 and cand <= budget_rows:
            r = cand
    return r


def fits_pallas_packed_tiled(height: int, width: int) -> bool:
    """Tiled eligibility: whole words, tile-aligned packed shape, and a
    strip that fits the budget (any board does once rows % 8 == 0 and a
    divisor-of-rows strip exists)."""
    if height % WORD != 0:
        return False
    rows = height // WORD
    if rows % 8 != 0 or width % 128 != 0:
        return False
    return 10 * width * 4 * 10 <= VMEM_BUDGET_BYTES  # min strip (8+2 rows)


#: Turns bought per halo word-row: the garbage frontier from the
#: extended strip's edge advances one bit-row per turn, so an h-word
#: halo keeps the strip interior exact for 32*h turns.
TILE_TURNS = WORD

#: Deepest supported halo: the neighbour-strip fetch is one 8-sublane
#: block, so at most 8 word-rows of halo exist to read.
MAX_HALO_WORDS = 8


def _halo_words(strip_rows: int, width: int,
                row_cost: int | None = None) -> int:
    """Halo depth (word-rows per side, 32*h turns per HBM pass): the
    deepest h whose extended-strip working set still fits scoped VMEM.
    Deeper halos amortize the per-pallas_call launch cost; past the
    VMEM knee the extra halo compute loses (measured: h=4 is ~7% over
    h=1 at 4096², h=8 regresses everywhere)."""
    row_cost = row_cost or width * 4 * 10
    for h in (4, 2, 1):
        if (strip_rows + 2 * h) * row_cost <= VMEM_BUDGET_BYTES:
            return h
    return 1


def _make_tiled_kernel(k_turns: int, rule: Rule, halo: int):
    assert 1 <= k_turns <= TILE_TURNS * halo
    assert 1 <= halo <= MAX_HALO_WORDS

    def kernel(up_ref, c_ref, dn_ref, out_ref):
        # Strip + `halo` word-rows from each neighbour strip's edge
        # block. Vertical shifts inside the extended strip use wrapped
        # rolls; the wrap feeds garbage into the outermost bit only,
        # advancing one bit-row per turn — interior rows stay exact for
        # k_turns <= 32*halo (the light-cone argument; tested bit-exact
        # at the boundary turn counts).
        p_ext = jnp.concatenate(
            [up_ref[8 - halo:], c_ref[:], dn_ref[:halo]], axis=0
        )
        out_ref[:] = _run_turns(p_ext, k_turns, rule)[halo:-halo]

    return kernel


def _tile_plan(rows: int, width: int, strip_rows: int | None,
               halo_words: int | None,
               row_cost: int | None = None) -> tuple:
    """Resolve (strip height, halo depth) once — the chunk size and the
    kernel's halo are always derived from the same pair."""
    r = strip_rows or _strip_rows(rows, width, row_cost)
    if rows % r != 0 or r % 8 != 0:
        raise ValueError(
            f"strip_rows={r} must divide the packed row count {rows} and "
            "be a multiple of 8"
        )
    if halo_words is None:
        h = _halo_words(r, width, row_cost)
    elif not 1 <= halo_words <= MAX_HALO_WORDS:
        raise ValueError(
            f"halo_words={halo_words} must be in 1..{MAX_HALO_WORDS} "
            "(the neighbour-strip fetch is one 8-sublane block)"
        )
    else:
        h = halo_words
    return r, h


def _tiled_call(p: jax.Array, k_turns: int, rule: Rule, interpret: bool,
                r: int, h: int):
    rows, width = p.shape
    nstrips = rows // r
    blocks = r // 8  # halo fetches are single 8-sublane blocks, so the
    # neighbour strips cost 8 rows of HBM traffic each, not r rows.
    up_spec = pl.BlockSpec(
        (8, width), lambda i: (((i - 1) % nstrips) * blocks + blocks - 1, 0)
    )
    dn_spec = pl.BlockSpec((8, width), lambda i: (((i + 1) % nstrips) * blocks, 0))
    return pl.pallas_call(
        _make_tiled_kernel(k_turns, rule, h),
        grid=(nstrips,),
        in_specs=[up_spec, pl.BlockSpec((r, width), lambda i: (i, 0)), dn_spec],
        out_specs=pl.BlockSpec((r, width), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, width), jnp.uint32),
        interpret=interpret,
    )(p, p, p)


@functools.partial(
    jax.jit,
    static_argnames=("n", "rule", "interpret", "strip_rows", "halo_words"),
)
def step_n_packed_pallas_tiled_raw(
    p: jax.Array,
    n: int,
    rule: Rule = LIFE,
    interpret: bool = False,
    strip_rows: int | None = None,
    halo_words: int | None = None,
) -> jax.Array:
    """`n` turns, packed in/out, strip-tiled with deep halos: each
    kernel invocation advances 32*h turns with one HBM round trip,
    where the halo depth h (word-rows per side) is auto-sized to scoped
    VMEM — 32-128x less HBM traffic than a per-turn XLA loop on boards
    too big for the whole-board kernel, with h>1 also amortizing the
    per-launch cost. `strip_rows`/`halo_words` override the auto
    sizing (strip_rows must divide the packed row count and be a
    multiple of 8; halo_words <= 8; tests use them to force
    multi-strip seams and light-cone-boundary turn counts on small
    boards)."""
    rows, width = p.shape
    r, h = _tile_plan(rows, width, strip_rows, halo_words)
    k = TILE_TURNS * h
    whole, rem = divmod(n, k)
    if whole:
        p = lax.fori_loop(
            0, whole,
            lambda _, q: _tiled_call(q, k, rule, interpret, r, h),
            p,
        )
    if rem:
        # The remainder needs only enough halo for its own light cone.
        h_rem = min(h, -(-rem // TILE_TURNS))
        p = _tiled_call(p, rem, rule, interpret, r, h_rem)
    return p


# --- 2-D tiled form (very wide boards) -------------------------------------
#
# The 1-D tiled kernel's strip height is bounded by VMEM *per row*
# (width x 4 x ~10 live arrays), so very wide boards get thin strips —
# and thin strips run far below the wide-op rate (measured at 2048²:
# r=16 strips reach 0.58x the whole-board kernel, r=64 strips 0.83x;
# the same short-dependency-chain wall as a small whole board). Tiling
# the WIDTH as well restores 64-row ops regardless of board width: each
# (r x TILE2D_WIDTH) tile is ghost-extended by h word-rows vertically
# AND TILE2D_GHOST_LANES columns horizontally (the horizontal light
# cone advances one column per turn, so 128 ghost columns match the
# 32*h turns of an h=4 ghost slab), with the 8 neighbour tiles'
# edges assembled in-kernel from nine block views of the same board.

#: Lane width of a 2-D tile (multiple of 128). 4096 measured 2.41
#: Tcells/s at 16384² vs 2.29 for 2048 (narrower tiles pay more column-
#: ghost redundancy); its working set only compiles because the edge
#: fetches are narrow TILE2D_FETCH_LANES blocks.
TILE2D_WIDTH = 4096
#: Ghost columns per side — one turn of horizontal light cone each.
TILE2D_GHOST_LANES = 128
#: Lane width of the neighbour-edge fetch blocks (the ghosts are
#: sliced from these in-kernel; a wider-than-ghost fetch keeps the
#: block shapes comfortably vreg-aligned).
TILE2D_FETCH_LANES = 512


def fits_pallas_packed_tiled2d(height: int, width: int) -> bool:
    """2-D tiling eligibility: packed tile alignment in both dims and a
    board wide enough that the 1-D strip budget is the binding
    constraint (narrower boards do better on the 1-D kernel's full-
    width strips)."""
    if height % WORD != 0:
        return False
    rows = height // WORD
    return (
        rows % 8 == 0
        and width % TILE2D_WIDTH == 0
        and width > TILE2D_WIDTH
        and rows >= 8
    )


def _tile2d_rows(total_rows: int) -> int:
    """Tile height (word rows): the 1-D strip search at the 2-D tile's
    fixed extended width — the per-row VMEM cost is width-independent
    here, so this resolves to the largest divisor of `total_rows` that
    is a multiple of 8 under STRIP_ROWS_CAP."""
    return _strip_rows(total_rows, TILE2D_WIDTH + 2 * TILE2D_GHOST_LANES)


def _make_tiled2d_kernel(k_turns: int, rule: Rule, halo: int, hw: int):
    assert 1 <= k_turns <= min(TILE_TURNS * halo, hw)
    assert 1 <= halo <= MAX_HALO_WORDS

    def kernel(ul_ref, ub_ref, ur_ref, l_ref, c_ref, r_ref,
               dl_ref, db_ref, dr_ref, out_ref):
        # Assemble the ghost frame: 8-row bands from the tile row above
        # and below (sliced to `halo` rows) and hw-lane edge blocks from
        # the horizontal neighbours — corners come from the diagonal
        # tiles, which the 8-neighbour stencil genuinely needs. All
        # ghost views are fetched as narrow blocks (hw lanes / 8 rows),
        # so the pipeline buffers stay small next to the extended tile.
        top = jnp.concatenate(
            [ul_ref[8 - halo:, -hw:], ub_ref[8 - halo:, :],
             ur_ref[8 - halo:, :hw]], axis=1,
        )
        mid = jnp.concatenate(
            [l_ref[:, -hw:], c_ref[:], r_ref[:, :hw]], axis=1
        )
        bot = jnp.concatenate(
            [dl_ref[:halo, -hw:], db_ref[:halo, :], dr_ref[:halo, :hw]],
            axis=1,
        )
        p_ext = jnp.concatenate([top, mid, bot], axis=0)
        # Toroidal wrap on the extended tile feeds garbage into the
        # outermost ghost ring only, advancing one row/column per turn
        # — the interior stays exact for k_turns <= min(32*halo, hw).
        out_ref[:] = _run_turns(p_ext, k_turns, rule)[halo:-halo, hw:-hw]

    return kernel


def _tiled2d_call(p: jax.Array, k_turns: int, rule: Rule, interpret: bool,
                  r: int, h: int):
    rows, width = p.shape
    wt = TILE2D_WIDTH
    specs = tiled2d_specs(rows, width, r, wt)
    return pl.pallas_call(
        _make_tiled2d_kernel(k_turns, rule, h, TILE2D_GHOST_LANES),
        grid=(rows // r, width // wt),
        in_specs=list(specs),
        out_specs=specs[4],  # the centre spec doubles as the out spec
        out_shape=jax.ShapeDtypeStruct((rows, width), jnp.uint32),
        interpret=interpret,
    )(*([p] * 9))


def tiled2d_specs(rows: int, width: int, r: int, wt: int) -> tuple:
    """The nine BlockSpecs of one 2-D ghost frame, in kernel order
    [up-left, up, up-right, left, centre, right, down-left, down,
    down-right] — vertical ghosts are single 8-sublane bands, the
    horizontal/corner ghosts narrow TILE2D_FETCH_LANES blocks sliced to
    the ghost width in-kernel. Shared with the per-plane generations
    kernel (ops/pallas_bitgens.py) so the grid index arithmetic has one
    definition."""
    fw = TILE2D_FETCH_LANES
    n, m = rows // r, width // wt
    blocks = r // 8   # vertical ghost fetches are single 8-sublane blocks
    lanes = wt // fw  # fetch-width units per tile (edge fetches narrow)

    def row_block(di, i):
        return ((i + di) % n) * blocks + (blocks - 1 if di < 0 else 0)

    def band(di, dj):
        # (8, wt) full-width band for dj=0; (8, fw) corner block else.
        if dj == 0:
            return pl.BlockSpec(
                (8, wt), lambda i, j, di=di: (row_block(di, i), j)
            )
        return pl.BlockSpec(
            (8, fw),
            lambda i, j, di=di, dj=dj: (
                row_block(di, i),
                ((j + dj) % m) * lanes + (lanes - 1 if dj < 0 else 0),
            ),
        )

    def edge(dj):
        return pl.BlockSpec(
            (r, fw),
            lambda i, j, dj=dj: (
                i, ((j + dj) % m) * lanes + (lanes - 1 if dj < 0 else 0)
            ),
        )

    return (band(-1, -1), band(-1, 0), band(-1, 1),
            edge(-1), pl.BlockSpec((r, wt), lambda i, j: (i, j)), edge(1),
            band(1, -1), band(1, 0), band(1, 1))


@functools.partial(
    jax.jit, static_argnames=("n", "rule", "interpret", "tile_rows")
)
def step_n_packed_pallas_tiled2d_raw(
    p: jax.Array,
    n: int,
    rule: Rule = LIFE,
    interpret: bool = False,
    tile_rows: int | None = None,
) -> jax.Array:
    """`n` turns, packed in/out, tiled in BOTH dimensions — the wide-
    board path (see the section comment above; measured 1.97 ->
    ~2.5 Tcells/s at 16384²). `tile_rows` overrides the auto height
    (tests force multi-tile seams on small boards)."""
    rows, width = p.shape
    r = tile_rows or _tile2d_rows(rows)
    if rows % r != 0 or r % 8 != 0:
        raise ValueError(f"tile_rows={r} must divide {rows} in 8-row units")
    h = _halo_words(r, TILE2D_WIDTH + 2 * TILE2D_GHOST_LANES)
    # Full-depth passes advance min(32h, ghost lanes) turns each.
    k = min(TILE_TURNS * h, TILE2D_GHOST_LANES)
    whole, rem = divmod(n, k)
    if whole:
        p = lax.fori_loop(
            0, whole,
            lambda _, q: _tiled2d_call(q, k, rule, interpret, r, h),
            p,
        )
    if rem:
        h_rem = min(h, -(-rem // TILE_TURNS))
        p = _tiled2d_call(p, rem, rule, interpret, r, h_rem)
    return p


@functools.partial(jax.jit, static_argnames=("n", "rule", "interpret"))
def step_n_pallas_packed(
    world: jax.Array,
    n: int,
    rule: Rule = LIFE,
    interpret: bool = False,
) -> jax.Array:
    """`n` turns on a {0,255} uint8 world via the packed VMEM kernel —
    drop-in for `ops.life.step_n` when `fits_pallas_packed(H, W)`."""
    h = world.shape[0]
    p = step_n_packed_pallas_raw(pack(to_bits(world)), n, rule, interpret)
    return from_bits(unpack(p, h))
