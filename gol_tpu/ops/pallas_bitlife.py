"""Pallas TPU kernel over the bit-packed board — packed SWAR x VMEM-resident.

`ops/pallas_life.py` keeps a dense board in VMEM; `ops/bitlife.py` packs
32 cells per uint32 word but runs under XLA's `fori_loop`, whose
loop-carried buffer lives in HBM. This kernel combines both wins: the
*packed* board (32x smaller) stays resident in VMEM for the entire
K-turn chunk — one HBM round trip per chunk, ~35 VPU bitwise ops per
32-cell word per turn (rule masks minimized by `ops/rulecomp.py`),
zero relayouts between turns.

Same layout and stencil as `ops/bitlife.py` (`packed[r, x]` holds rows
`32r..32r+31` of column `x`); vertical toroidal shifts are word
bit-shifts with cross-word carries fetched by `pltpu.roll` on the
sublane axis, horizontal shifts are `pltpu.roll` on the lane axis. The
CSA count tree and rule minterm masks are imported from `bitlife` —
one definition of the packed rule engine's arithmetic.

Bit-exactness vs the XLA packed path is asserted in tests (interpreter
mode on CPU + golden boards). Serial-sweep analog of
ref: gol/distributor.go:350-379, done as a resident-VMEM packed kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.models.rules import LIFE, Rule
from gol_tpu.ops.bitlife import WORD, combine_packed, pack, unpack
from gol_tpu.ops.life import from_bits, to_bits

#: VMEM budget for board + live CSA temporaries (the packed board is
#: H*W/8 bytes; the adder tree keeps ~8 word-arrays live at peak).
VMEM_BUDGET_BYTES = 12 << 20


def fits_pallas_packed(height: int, width: int) -> bool:
    """Whole-packed-board-in-VMEM eligibility: whole 32-row words, TPU
    tile-aligned packed shape (sublanes % 8, lanes % 128), and the
    working set within budget."""
    if height % WORD != 0:
        return False
    rows = height // WORD
    if rows % 8 != 0 or width % 128 != 0:
        return False
    return rows * width * 4 * 10 <= VMEM_BUDGET_BYTES


def _pallas_turn(p: jax.Array, rule: Rule) -> jax.Array:
    """One packed turn inside a kernel: vertical toroidal shifts via
    sublane rolls + cross-word carry bits, then the shared column-sum
    rule combine with `pltpu.roll` as the lane-roll primitive. Shifts
    use plain ints (not traced uint32 scalars) so the kernel body closes
    over no constants — pallas requires a closed jaxpr."""
    one, top = 1, WORD - 1
    rows = p.shape[0]
    up = (p << one) | (pltpu.roll(p, 1, 0) >> top)
    down = (p >> one) | (pltpu.roll(p, rows - 1, 0) << top)
    return combine_packed(p, up, down, rule, roll=pltpu.roll)


#: Turns per loop iteration inside the kernels. Mosaic lowers
#: `fori_loop` to a scalar-core loop whose per-iteration overhead is
#: visible on small boards (a packed 512² board is only 8 vregs of
#: vector work per turn); hand-unrolling 8 turns per iteration buys
#: ~5-8% at 512² and is neutral on large boards. Mosaic itself only
#: supports unroll=1 or full unroll, hence the nested form.
UNROLL = 8


def _turns_body(rule: Rule, unroll: int):
    def body(_, p):
        for _ in range(unroll):
            p = _pallas_turn(p, rule)
        return p

    return body


def _run_turns(p: jax.Array, n_turns: int, rule: Rule) -> jax.Array:
    """`n_turns` in-kernel turns: an UNROLL-deep loop plus remainder."""
    whole, rem = divmod(n_turns, UNROLL)
    if whole:
        p = lax.fori_loop(0, whole, _turns_body(rule, UNROLL), p)
    for _ in range(rem):
        p = _pallas_turn(p, rule)
    return p


def _make_kernel(n_turns: int, rule: Rule):
    def kernel(in_ref, out_ref):
        out_ref[:] = _run_turns(in_ref[:], n_turns, rule)

    return kernel


@functools.partial(jax.jit, static_argnames=("n", "rule", "interpret"))
def step_n_packed_pallas_raw(
    p: jax.Array,
    n: int,
    rule: Rule = LIFE,
    interpret: bool = False,
) -> jax.Array:
    """`n` turns, packed uint32 in / packed uint32 out, one kernel call."""
    return pl.pallas_call(
        _make_kernel(n, rule),
        out_shape=jax.ShapeDtypeStruct(p.shape, jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(p)


def _strip_rows(total_rows: int, width: int) -> int:
    """Strip height (word rows) for the tiled kernel: largest divisor of
    `total_rows` that is a multiple of 8 and keeps the strip working set
    ((R+2) x width x ~10 live arrays) within budget."""
    budget_rows = VMEM_BUDGET_BYTES // (width * 4 * 10) - 2
    r = 8
    for cand in range(8, total_rows + 1, 8):
        if total_rows % cand == 0 and cand <= budget_rows:
            r = cand
    return r


def fits_pallas_packed_tiled(height: int, width: int) -> bool:
    """Tiled eligibility: whole words, tile-aligned packed shape, and a
    strip that fits the budget (any board does once rows % 8 == 0 and a
    divisor-of-rows strip exists)."""
    if height % WORD != 0:
        return False
    rows = height // WORD
    if rows % 8 != 0 or width % 128 != 0:
        return False
    return 10 * width * 4 * 10 <= VMEM_BUDGET_BYTES  # min strip (8+2 rows)


#: Max turns per tiled kernel invocation: the 1-word-row (32-bit) halo
#: absorbs exactly one bit of invalid-edge propagation per turn.
TILE_TURNS = WORD


def _make_tiled_kernel(k_turns: int, rule: Rule):
    assert 1 <= k_turns <= TILE_TURNS

    def kernel(up_ref, c_ref, dn_ref, out_ref):
        # Strip + one halo word row from each neighbour strip. Vertical
        # shifts inside the extended strip use wrapped rolls; the wrap
        # feeds garbage into the halo's *outer* bit only, which crosses
        # the 32-bit halo word in 32 turns — interior rows stay exact
        # for k_turns <= 32 (the light-cone argument; tested bit-exact).
        p_ext = jnp.concatenate(
            [up_ref[-1:], c_ref[:], dn_ref[:1]], axis=0
        )
        out_ref[:] = _run_turns(p_ext, k_turns, rule)[1:-1]

    return kernel


def _tiled_call(p: jax.Array, k_turns: int, rule: Rule, interpret: bool,
                strip_rows: int | None = None):
    rows, width = p.shape
    r = strip_rows or _strip_rows(rows, width)
    if rows % r != 0 or r % 8 != 0:
        raise ValueError(
            f"strip_rows={r} must divide the packed row count {rows} and "
            "be a multiple of 8"
        )
    nstrips = rows // r
    blocks = r // 8  # halo fetches are single 8-sublane blocks, so the
    # neighbour strips cost 8 rows of HBM traffic each, not r rows.
    up_spec = pl.BlockSpec(
        (8, width), lambda i: (((i - 1) % nstrips) * blocks + blocks - 1, 0)
    )
    dn_spec = pl.BlockSpec((8, width), lambda i: (((i + 1) % nstrips) * blocks, 0))
    return pl.pallas_call(
        _make_tiled_kernel(k_turns, rule),
        grid=(nstrips,),
        in_specs=[up_spec, pl.BlockSpec((r, width), lambda i: (i, 0)), dn_spec],
        out_specs=pl.BlockSpec((r, width), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, width), jnp.uint32),
        interpret=interpret,
    )(p, p, p)


@functools.partial(
    jax.jit, static_argnames=("n", "rule", "interpret", "strip_rows")
)
def step_n_packed_pallas_tiled_raw(
    p: jax.Array,
    n: int,
    rule: Rule = LIFE,
    interpret: bool = False,
    strip_rows: int | None = None,
) -> jax.Array:
    """`n` turns, packed in/out, strip-tiled: each kernel invocation
    advances TILE_TURNS turns with one HBM round trip — 32x less HBM
    traffic than a per-turn XLA loop on boards too big for the
    whole-board kernel. `strip_rows` overrides the auto strip height
    (must divide the packed row count and be a multiple of 8; tests use
    it to force multi-strip seams on small boards)."""
    whole, rem = divmod(n, TILE_TURNS)
    if whole:
        p = lax.fori_loop(
            0, whole,
            lambda _, q: _tiled_call(q, TILE_TURNS, rule, interpret, strip_rows),
            p,
        )
    if rem:
        p = _tiled_call(p, rem, rule, interpret, strip_rows)
    return p


@functools.partial(jax.jit, static_argnames=("n", "rule", "interpret"))
def step_n_pallas_packed(
    world: jax.Array,
    n: int,
    rule: Rule = LIFE,
    interpret: bool = False,
) -> jax.Array:
    """`n` turns on a {0,255} uint8 world via the packed VMEM kernel —
    drop-in for `ops.life.step_n` when `fits_pallas_packed(H, W)`."""
    h = world.shape[0]
    p = step_n_packed_pallas_raw(pack(to_bits(world)), n, rule, interpret)
    return from_bits(unpack(p, h))
