"""Trace-time rule compiler — minimized boolean masks for packed stepping.

The packed engine (`ops/bitlife.py`) evaluates the B/S rule on 4 count
bit-slices. The naive form ORs one 4-literal minterm per count in the
birth/survive sets (~15 VPU ops for B3/S23). But an 8-neighbour count
can never exceed 8, so the bit patterns 9..15 are *don't-cares* — a
Quine-McCluskey minimization over them collapses the masks dramatically
(B3/S23's survive mask {2,3} becomes the single implicant `b1 & ~b2`).

Everything here runs at trace time on static python rule data, per the
XLA compilation model: the compiled plan is pure structure (implicant
tuples), and `emit_mask` replays it as bitwise ops on whatever array
type the caller traces with (XLA arrays or pallas VMEM loads alike).

The compiler also reports which count bits the minimized masks actually
read (`RulePlan.needed`), so the carry-save adder can skip materializing
unused slices (B3/S23 never needs bit 3), and classifies the
birth/survive relationship so the final combine can use the cheaper
`B | (p & S)` form when birth ⊆ survive instead of the generic
`(p & S) | (~p & B)`.

The reference hard-codes B3/S23 as per-cell comparisons
(ref: gol/distributor.go:325-342); here any life-like rule compiles to
a near-minimal fused bitwise expression.
"""

from __future__ import annotations

import dataclasses
import functools

from gol_tpu.models.rules import Rule

#: Number of count bit-slices (8 neighbours -> counts 0..8 need 4 bits).
NBITS = 4

#: Bit patterns a neighbour count can actually take.
_REACHABLE = frozenset(range(9))

#: ... and the patterns it cannot (the minimizer's don't-care set).
DONT_CARES = frozenset(range(9, 1 << NBITS))

#: An implicant: (value, care) bit masks over the NBITS count bits —
#: it covers count c iff (c & care) == value. care == 0 covers all.
Implicant = tuple


def _covers(imp: Implicant, m: int) -> bool:
    value, care = imp
    return (m & care) == value


def _prime_implicants(terms: frozenset) -> set:
    """All prime implicants of the given minterm set (Quine-McCluskey
    combine passes: merge pairs differing in exactly one cared bit)."""
    primes: set = set()
    cur = {(m, (1 << NBITS) - 1) for m in terms}
    while cur:
        merged: set = set()
        used: set = set()
        lst = sorted(cur)
        for i, (v1, c1) in enumerate(lst):
            for v2, c2 in lst[i + 1:]:
                if c1 != c2:
                    continue
                d = (v1 ^ v2) & c1
                if d and (d & (d - 1)) == 0:  # differ in exactly one bit
                    merged.add((v1 & ~d, c1 & ~d))
                    used.add((v1, c1))
                    used.add((v2, c2))
        primes |= cur - used
        cur = merged
    return primes


def _select_cover(primes: set, minterms: frozenset) -> tuple:
    """Minimal-ish prime cover of the minterms: essential implicants
    first, then greedy by coverage (4 variables — greedy is exact or
    within one term on everything life-like; determinism matters more)."""
    remaining = set(minterms)
    ordered = sorted(primes)
    chosen: list = []
    while remaining:
        essential = None
        for m in sorted(remaining):
            cov = [p for p in ordered if _covers(p, m)]
            if len(cov) == 1:
                essential = cov[0]
                break
        if essential is None:
            essential = max(
                ordered,
                key=lambda p: (
                    sum(1 for m in remaining if _covers(p, m)),
                    -bin(p[1]).count("1"),
                    (-p[0], -p[1]),  # deterministic tie-break
                ),
            )
        if essential not in chosen:
            chosen.append(essential)
        remaining -= {m for m in remaining if _covers(essential, m)}
    return tuple(sorted(chosen))


def minimize_counts(counts: frozenset) -> tuple:
    """Minimized implicant cover of `counts` ⊆ {0..8}, free to behave
    arbitrarily on the unreachable patterns 9..15."""
    counts = frozenset(counts) & _REACHABLE
    if not counts:
        return ()
    primes = _prime_implicants(counts | DONT_CARES)
    return _select_cover(primes, counts)


@dataclasses.dataclass(frozen=True)
class RulePlan:
    """A compiled rule: minimized survive/birth implicant covers, the
    count bits they read, and the cheapest final-combine form."""

    survive: tuple
    birth: tuple
    needed: frozenset  # count-bit indices any implicant cares about
    combine: str  # 'b_subset' | 's_subset' | 'general'

    def mask_cost(self) -> int:
        """Op count of both masks exactly as emitted: replays
        `emit_mask` (shared cache and all) over counting stand-ins for
        the bit slices, so it cannot drift from the real emission."""
        ops = [0]

        class _Bit:
            def __and__(self, other):
                ops[0] += 1
                return _Bit()

            __or__ = __and__

            def __invert__(self):
                ops[0] += 1
                return _Bit()

        bits = {i: _Bit() for i in range(NBITS)}
        cache: dict = {}
        for cover in (self.survive, self.birth):
            if cover and not is_full(cover):
                emit_mask(cover, bits, cache)
        return ops[0]


def _literals(imp: Implicant) -> tuple:
    """Cared literals, high bit first: life-like rules constrain the
    high count bits the same way in birth and survive (a board cell has
    ≤8 neighbours, so masks mostly say "count < 4, then..."), so this
    order maximizes shared product prefixes between the two masks."""
    value, care = imp
    return tuple(
        (i, bool(value & (1 << i)))
        for i in range(NBITS - 1, -1, -1)
        if care & (1 << i)
    )


@functools.lru_cache(maxsize=None)
def compile_rule(rule: Rule) -> RulePlan:
    survive = minimize_counts(rule.survive)
    birth = minimize_counts(rule.birth)
    needed = frozenset(
        i for cover in (survive, birth) for imp in cover
        for i, _ in _literals(imp)
    )
    b, s = frozenset(rule.birth) & _REACHABLE, frozenset(rule.survive) & _REACHABLE
    if b <= s:
        combine = "b_subset"  # next = B | (p & S)
    elif s <= b:
        combine = "s_subset"  # next = S | (~p & B)
    else:
        combine = "general"  # next = (p & S) | (~p & B)
    return RulePlan(survive=survive, birth=birth, needed=needed,
                    combine=combine)


def emit_mask(cover: tuple, bits: dict, cache: dict):
    """Build the OR-of-products array for an implicant cover.

    `bits` maps count-bit index -> bit-slice array; `cache` memoizes
    NOT-literals and product prefixes so terms shared between the
    survive and birth masks (pass the same dict) are computed once —
    pallas/Mosaic is not guaranteed to CSE across expressions, so the
    sharing is done here, structurally.

    Returns None for an empty cover (mask identically 0); a full-ones
    mask (care == 0 implicant) comes back as ~(b & ~b)-free: the caller
    checks `cover == ((0, 0),)` via `is_full` instead, since no
    all-ones constant exists without knowing the array shape.
    """
    terms = []
    for imp in cover:
        lits = _literals(imp)
        if not lits:  # covers everything; caller must special-case
            raise ValueError("full cover has no array form; use is_full")
        prefix: tuple = ()
        acc = None
        for lit in lits:
            prefix += (lit,)
            if prefix in cache:
                acc = cache[prefix]
                continue
            idx, positive = lit
            if positive:
                literal = bits[idx]
            elif ("~", idx) in cache:
                literal = cache[("~", idx)]
            else:
                literal = ~bits[idx]
                cache[("~", idx)] = literal
            acc = literal if acc is None else acc & literal
            cache[prefix] = acc
        terms.append(acc)
    if not terms:
        return None
    out = terms[0]
    for t in terms[1:]:
        out = out | t
    return out


def is_full(cover: tuple) -> bool:
    """True iff the cover contains the care-nothing implicant (mask is
    identically all-ones on reachable counts)."""
    return any(care == 0 for _, care in cover)


def evaluate_cover(cover: tuple, count: int) -> bool:
    """Reference evaluator (tests): does the minimized cover accept this
    count pattern?"""
    return any(_covers(imp, count) for imp in cover)
