"""Bit-packed Game of Life — 32 cells per uint32 word, SWAR stepping.

The dense path (`ops/life.py`) spends one vector lane per cell. Packing
32 vertically-adjacent cells into each uint32 word turns the stencil
into pure bitwise arithmetic on a 32x-smaller array: the 8 neighbour
bitboards come from word shifts (vertical, with cross-word carries) and
lane rolls (horizontal), and the neighbour count is computed in bit
slices with a carry-save adder tree — ~35 bitwise ops per turn for the
whole board instead of ~15 vector ops per *cell-lane*.

Layout: `packed[r, x]` holds rows `32r .. 32r+31` of column `x`; bit `i`
(LSB first) is row `32r + i`. Toroidal wrap in both axes falls out of
`jnp.roll` on the word rows plus the cross-word carry bits.

Rule-generic: the 4 count bits (0..8 needs 4) feed masks compiled at
trace time by `ops/rulecomp.py` (Quine-McCluskey with counts 9..15 as
don't-cares, shared products, subset-factored combine) — any B/S rule
becomes a near-minimal fused bitwise expression (B3/S23 is the
reference rule, ref: gol/distributor.go:325-342).

Bit-exactness vs the dense path is asserted in tests; the automaton is
integer-deterministic so equality is exact, not approximate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from gol_tpu.models.rules import LIFE, Rule
from gol_tpu.ops import rulecomp
from gol_tpu.ops.life import from_bits, to_bits

WORD = 32


def packable(height: int, width: int) -> bool:
    """The packed path needs whole words per column strip."""
    del width
    return height % WORD == 0 and height >= WORD


def pack_np(world) -> "np.ndarray":
    """Host-side pack: {0,255} (H, W) uint8 -> uint32 (H/32, W). Mirrors
    `pack(to_bits(...))` without touching a device — multihost `put`
    packs on the host so each process can slice its own shard."""
    import numpy as np

    bits = (np.asarray(world) != 0).astype(np.uint32)
    h, w = bits.shape
    words = bits.reshape(h // WORD, WORD, w)
    weights = (np.uint32(1) << np.arange(WORD, dtype=np.uint32))[None, :, None]
    return (words * weights).sum(axis=1, dtype=np.uint32)


def unpack_np(packed, height: int) -> "np.ndarray":
    """Host-side unpack: uint32 (H/32, W) -> {0,255} uint8 (H, W)."""
    import numpy as np

    packed = np.asarray(packed)
    shifts = np.arange(WORD, dtype=np.uint32)[None, :, None]
    words = (packed[:, None, :] >> shifts) & np.uint32(1)
    return (words.reshape(height, packed.shape[1]) * np.uint8(255)).astype(
        np.uint8
    )


def pack(bits: jax.Array) -> jax.Array:
    """{0,1} (H, W) -> uint32 (H/32, W), bit i of word r = row 32r+i."""
    h, w = bits.shape
    words = bits.astype(jnp.uint32).reshape(h // WORD, WORD, w)
    weights = (jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32))[None, :, None]
    return jnp.sum(words * weights, axis=1, dtype=jnp.uint32)


def unpack(packed: jax.Array, height: int) -> jax.Array:
    """uint32 (H/32, W) -> {0,1} uint8 (H, W)."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)[None, :, None]
    words = (packed[:, None, :] >> shifts) & jnp.uint32(1)
    return words.reshape(height, packed.shape[1]).astype(jnp.uint8)


def _shift_up(p: jax.Array) -> jax.Array:
    """result[y] = orig[y-1] (toroidal): bits move up one row index."""
    carry = jnp.roll(p, 1, axis=0) >> jnp.uint32(WORD - 1)
    return (p << jnp.uint32(1)) | carry


def _shift_down(p: jax.Array) -> jax.Array:
    """result[y] = orig[y+1] (toroidal)."""
    carry = jnp.roll(p, -1, axis=0) << jnp.uint32(WORD - 1)
    return (p >> jnp.uint32(1)) | carry


#: Sentinel for an all-ones mask (a cover containing the care-nothing
#: implicant); compared with `is` — jax arrays overload `==`.
ONE = object()


def rule_masks(p: jax.Array, up: jax.Array, down: jax.Array,
               plan: rulecomp.RulePlan, roll=None) -> tuple:
    """(survive, birth) masks of the compiled plan over the CSA
    neighbour count — each an array, None (identically zero), or the
    `ONE` sentinel (identically ones). The single definition of the
    packed count arithmetic, shared by the life-like combine below and
    the generations planes (ops/bitgens.py).

    Column-sum form: the 8-neighbour count is (left column sum) +
    (right column sum) + (up + down), where each column sum is the
    2-bit CSA of a vertical triple — 4 lane rolls (of the two
    column-sum bit slices) instead of 6 (of p/up/down), and a 3x2-bit
    adder instead of an 8x1-bit one. Count bit-slices are materialized
    only if some minimized implicant reads them."""
    if roll is None:
        roll = jnp.roll
    need = plan.needed
    # Vertical triple (up + p + down) as 2 bit slices.
    upd = up ^ down
    pc = up & down
    vs = upd ^ p
    vc = pc | (p & upd)
    ls, lc = roll(vs, 1, 1), roll(vc, 1, 1)
    w = p.shape[1]
    rs, rc = roll(vs, w - 1, 1), roll(vc, w - 1, 1)
    # count = (ls,lc) + (rs,rc) + (up+down as (upd, pc)).
    x = ls ^ rs
    k0 = (ls & rs) | (upd & x)           # carry out of bit 0
    y = lc ^ rc
    t1 = y ^ pc                          # sum of the bit-1 slices
    k1 = (lc & rc) | (pc & y)            # their carry into bit 2
    bits: dict = {}
    if 0 in need:
        bits[0] = x ^ upd
    if 1 in need:
        bits[1] = t1 ^ k0
    if 2 in need or 3 in need:
        k2 = t1 & k0
        if 2 in need:
            bits[2] = k1 ^ k2
        if 3 in need:
            bits[3] = k1 & k2
    cache: dict = {}

    def mask(cover):
        if rulecomp.is_full(cover):
            return ONE
        return rulecomp.emit_mask(cover, bits, cache)

    return mask(plan.survive), mask(plan.birth)


def resolve_mask(m, like: jax.Array) -> jax.Array:
    """Materialize a rule_masks result as an array (for callers that
    cannot exploit the zero/ones sentinels structurally)."""
    if m is None:
        return like ^ like
    if m is ONE:
        return ~(like ^ like)
    return m


def _combine_masks(p: jax.Array, plan: rulecomp.RulePlan,
                   survive, birth) -> jax.Array:
    """Final combine of the minimized survive/birth masks with the
    current board, in the cheapest form the plan classified (see
    rulecomp.compile_rule)."""

    def AND(x, m):
        if m is None:
            return None
        if m is ONE:
            return x
        return x & m

    def OR(a, b):
        if a is None:
            return b
        if b is None:
            return a
        if a is ONE or b is ONE:
            return ONE
        return a | b

    if plan.combine == "b_subset":
        out = OR(birth, AND(p, survive))
    elif plan.combine == "s_subset":
        out = OR(survive, AND(~p, birth))
    else:
        out = OR(AND(p, survive), AND(~p, birth))
    if out is None:
        return p ^ p
    if out is ONE:
        return ~(p ^ p)
    return out


def combine_packed(p: jax.Array, up: jax.Array, down: jax.Array,
                   rule: Rule, roll=None) -> jax.Array:
    """Horizontal rolls + CSA count + rule combine, given the two
    vertically-shifted bitboards. The single definition of the packed
    rule engine — the single-chip path supplies toroidal shifts, the
    sharded path supplies halo-carried ones (parallel/packed_halo.py),
    and the pallas kernels supply `roll` (pltpu.roll) to stay on the
    VPU. The count arithmetic + minimized mask emission live in
    `rule_masks`; this adds the subset-factored final combine."""
    plan = rulecomp.compile_rule(rule)
    survive, birth = rule_masks(p, up, down, plan, roll)
    return _combine_masks(p, plan, survive, birth)


def step_packed(p: jax.Array, rule: Rule = LIFE) -> jax.Array:
    """One turn on a packed board."""
    return combine_packed(p, _shift_up(p), _shift_down(p), rule)


def make_codec(height: int):
    """Jitted (pack_world, unpack_world, fetch) trio shared by the packed
    stepper backends: pack a {0,255} world to words, unpack words back,
    and a host `fetch` that dispatches on dtype (packed uint32 worlds are
    unpacked; anything else — e.g. dense bool diff masks — passes
    through). One definition, so the wire convention cannot diverge
    between the single-device and sharded packed paths."""
    import numpy as _np

    @jax.jit
    def pack_world(world):
        return pack(to_bits(world))

    @jax.jit
    def unpack_world(p):
        return from_bits(unpack(p, height))

    def fetch(arr):
        if arr.dtype == jnp.uint32:
            return _np.asarray(unpack_world(arr))
        return _np.asarray(arr)

    return pack_world, unpack_world, fetch


def step_n_packed_raw(p: jax.Array, n: int, rule: Rule = LIFE) -> jax.Array:
    """`n` turns, packed in / packed out — the loop the packed stepper
    and the world-level wrappers share."""
    return lax.fori_loop(0, n, lambda _, q: step_packed(q, rule), p)


def count_packed(p: jax.Array) -> jax.Array:
    """Alive count of a packed board (popcount reduction)."""
    return jnp.sum(lax.population_count(p).astype(jnp.int32), dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("n", "rule"))
def step_n_packed(world: jax.Array, n: int, rule: Rule = LIFE) -> jax.Array:
    """`n` turns on a {0,255} uint8 world via the packed representation —
    drop-in for `ops.life.step_n` when `packable(H, W)`."""
    h = world.shape[0]
    p = step_n_packed_raw(pack(to_bits(world)), n, rule)
    return from_bits(unpack(p, h))


@functools.partial(jax.jit, static_argnames=("n", "rule"))
def step_n_counted_packed(world: jax.Array, n: int, rule: Rule = LIFE):
    """`n` turns + alive count (popcount over the packed words)."""
    h = world.shape[0]
    p = step_n_packed_raw(pack(to_bits(world)), n, rule)
    return from_bits(unpack(p, h)), count_packed(p)
