from gol_tpu.ops.life import (
    ALIVE,
    alive_cells,
    alive_count,
    from_bits,
    neighbour_counts,
    step,
    step_n,
    step_with_diff,
    to_bits,
)

__all__ = [
    "ALIVE",
    "alive_cells",
    "alive_count",
    "from_bits",
    "neighbour_counts",
    "step",
    "step_n",
    "step_with_diff",
    "to_bits",
]
