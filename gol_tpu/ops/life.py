"""Core Game of Life step kernels — single fused XLA ops, TPU-first.

The reference computes each next cell with 8 bounds-wrapped scalar reads
(`checkNeighbour`, ref: gol/distributor.go:382-417) inside a Go
double-loop (serial sweep ref: gol/distributor.go:350-379; per-row worker
sweep ref: gol/distributor.go:318-347). The TPU-native design replaces
all of that with whole-array vector ops: a separable toroidal 3×3 sum
(two `jnp.roll` pairs — 4 shifted adds instead of 8), then the B/S rule
as a fused boolean combine. XLA fuses the entire step into one
elementwise kernel; on TPU the rolls become cheap lane/sublane rotations,
and the automaton being integer-valued makes bit-exactness automatic.

Everything here is shape-polymorphic and `jit`/`shard_map`-safe: no
data-dependent python control flow, static shapes, `lax.fori_loop` for
the multi-turn path.
"""

from __future__ import annotations

import functools
import operator

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.models.rules import LIFE, Rule, get_rule
from gol_tpu.utils.cell import Cell, cells_from_mask

#: Alive pixel value — the grid is 2-valued {0, 255} like the reference's
#: PGM world (ref: gol/io.go raster; README.md:24-31).
ALIVE = 255


def to_bits(world: jax.Array) -> jax.Array:
    """{0,255} uint8 world -> {0,1} uint8 occupancy."""
    return (world != 0).astype(jnp.uint8)


def from_bits(bits: jax.Array) -> jax.Array:
    """{0,1} occupancy -> {0,255} uint8 world."""
    return bits.astype(jnp.uint8) * jnp.uint8(ALIVE)


def neighbour_counts(bits: jax.Array) -> jax.Array:
    """8-neighbour counts with toroidal wraparound.

    Separable: vertical 3-sum then horizontal 3-sum of that, minus the
    centre — 4 rolls + 5 adds for what the reference does with 8
    wrapped reads per cell (ref: gol/distributor.go:382-417). `jnp.roll`
    on a sharded axis lowers to a ring CollectivePermute of one boundary
    row under the SPMD partitioner, so this same kernel is the halo
    exchange when the grid is sharded.
    """
    v = bits + jnp.roll(bits, 1, 0) + jnp.roll(bits, -1, 0)
    n = v + jnp.roll(v, 1, 1) + jnp.roll(v, -1, 1)
    return n - bits


def count_in(counts: jax.Array, ns) -> jax.Array:
    """Membership mask `counts ∈ ns` for a static neighbour-count set —
    unrolls to compares + ors at trace time (shared by the dense B/S
    combine below and the generations family, ops/generations.py)."""
    terms = [counts == k for k in sorted(ns)]
    if not terms:
        return jnp.zeros(counts.shape, jnp.bool_)
    return functools.reduce(operator.or_, terms)


def apply_rule(bits: jax.Array, counts: jax.Array, rule: Rule) -> jax.Array:
    """B/S rule as a fused boolean combine over static neighbour sets.

    The rule's birth/survive sets are compile-time python data, so this
    unrolls to a handful of compares and ors that XLA fuses with the
    neighbour sum — no gather, no table lookup at runtime.
    """
    alive = bits != 0
    nxt = jnp.where(alive, count_in(counts, rule.survive),
                    count_in(counts, rule.birth))
    return nxt.astype(jnp.uint8)


def step_bits(bits: jax.Array, rule: Rule = LIFE) -> jax.Array:
    """One turn on a {0,1} grid."""
    return apply_rule(bits, neighbour_counts(bits), rule)


def _resolve(rule: Rule | str | None) -> Rule:
    if rule is None:
        return LIFE
    if isinstance(rule, str):
        return get_rule(rule)
    return rule


@functools.partial(jax.jit, static_argnames=("rule",))
def step(world: jax.Array, rule: Rule | str = LIFE) -> jax.Array:
    """One turn on a {0,255} uint8 world (the serial-engine analog,
    ref: gol/distributor.go:350-379)."""
    return from_bits(step_bits(to_bits(world), _resolve(rule)))


@functools.partial(jax.jit, static_argnames=("n", "rule"))
def step_n(world: jax.Array, n: int, rule: Rule | str = LIFE) -> jax.Array:
    """`n` turns fused into one dispatch via `lax.fori_loop` — the chunked
    on-device turn loop (the host only sees the world every chunk)."""
    rule = _resolve(rule)
    bits = to_bits(world)
    bits = lax.fori_loop(0, n, lambda _, b: step_bits(b, rule), bits)
    return from_bits(bits)


@functools.partial(jax.jit, static_argnames=("n", "rule"))
def step_n_counted(world: jax.Array, n: int, rule: Rule | str = LIFE):
    """`n` turns plus the resulting alive count, fused into one program —
    the engine's fast path (one dispatch, one collective rendezvous)."""
    rule = _resolve(rule)
    bits = to_bits(world)
    bits = lax.fori_loop(0, n, lambda _, b: step_bits(b, rule), bits)
    return from_bits(bits), jnp.sum(bits, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("rule",))
def step_with_diff(world: jax.Array, rule: Rule | str = LIFE):
    """One turn plus the flipped-cell mask plus the alive count — the
    device-side analog of the reference's per-turn diff scan that feeds
    `CellFlipped` events (ref: gol/distributor.go:212-220). The mask
    ships to the host in one bulk transfer instead of one event per cell."""
    bits = step_bits(to_bits(world), _resolve(rule))
    new = from_bits(bits)
    return new, world != new, jnp.sum(bits, dtype=jnp.int32)


@jax.jit
def alive_count(world: jax.Array) -> jax.Array:
    """Number of alive cells (ref: gol/distributor.go:420-432). Under a
    sharded world this is a partial sum + `psum` inserted by XLA."""
    return jnp.sum(world != 0, dtype=jnp.int32)


def alive_cells(world) -> list[Cell]:
    """Host-side alive-cell set as Cell(x=col, y=row) — the payload of
    `FinalTurnComplete` (ref: gol/distributor.go:420-432, gol/event.go:65-68)."""
    return cells_from_mask(world)


def flipped_cells(mask) -> list[Cell]:
    """Host-side coordinates of a diff mask, as Cell(x, y)."""
    return cells_from_mask(mask)


def random_world(height: int, width: int, density: float = 0.25, seed: int = 0):
    """Random {0,255} world for benchmarks (no reference analog — the
    reference always seeds from images/; used for the 4096² stress runs)."""
    rng = np.random.default_rng(seed)
    return (rng.random((height, width)) < density).astype(np.uint8) * np.uint8(ALIVE)
