"""Pallas TPU kernel for the Game of Life step — the hot-op fast path.

The XLA path (`ops/life.py`) runs one fused elementwise program per turn
inside `lax.fori_loop`; each turn still reads and writes the board in
HBM. This kernel keeps the whole board resident in VMEM and runs the
entire K-turn chunk inside ONE kernel invocation — per turn: four
`pltpu.roll`s (toroidal separable 3-sum) plus the B/S combine, all on
the VPU, zero HBM traffic between turns. The board makes exactly one
HBM→VMEM→HBM round trip per chunk.

Correctness is identical by construction (same integer stencil, same
rule combine as `ops/life.apply_rule`); tests run the kernel in
interpreter mode on CPU against the XLA path and the golden boards.

Eligibility (`fits_pallas`): board + working set within a VMEM budget
and TPU-friendly shape (sublane multiple of 8, lane multiple of 128).
Callers fall back to the XLA path otherwise; oversized boards get the
XLA path's sharded/tiled treatment instead (parallel/halo.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.models.rules import LIFE, Rule
from gol_tpu.ops.life import apply_rule, from_bits, to_bits

#: VMEM working-set budget: board (int32 in-kernel) x ~5 live temporaries,
#: kept well under the ~16 MB/core ceiling.
VMEM_BUDGET_BYTES = 12 << 20


def fits_pallas(height: int, width: int) -> bool:
    """Whole-board-in-VMEM eligibility (shape tiling + memory budget)."""
    if height % 8 != 0 or width % 128 != 0:
        return False
    return height * width * 4 * 5 <= VMEM_BUDGET_BYTES


def _roll(x, shift: int, axis: int):
    # pltpu.roll rejects negative shifts; a circular shift by -1 is a
    # shift by dim-1.
    return pltpu.roll(x, shift % x.shape[axis], axis)


def _make_kernel(n_turns: int, rule: Rule):
    # The rule combine as pure int32 arithmetic — mosaic rejects the
    # narrow-int truncations `apply_rule`'s bool/uint8 dance produces, so
    # membership in the static birth/survive sets becomes a sum of
    # (counts == k) indicators and the select becomes a multiply:
    #   next = alive * survive(counts) + (1 - alive) * birth(counts)
    def indicator(counts, ns):
        if not ns:
            return jnp.zeros_like(counts)
        return sum((counts == k).astype(jnp.int32) for k in sorted(ns))

    def kernel(in_ref, out_ref):
        def turn(_, bits):
            v = bits + _roll(bits, 1, 0) + _roll(bits, -1, 0)
            counts = v + _roll(v, 1, 1) + _roll(v, -1, 1) - bits
            surv = indicator(counts, rule.survive)
            born = indicator(counts, rule.birth)
            return bits * surv + (1 - bits) * born

        out_ref[:] = lax.fori_loop(0, n_turns, turn, in_ref[:])

    return kernel


@functools.partial(jax.jit, static_argnames=("n", "rule", "interpret"))
def step_n_pallas(
    world: jax.Array,
    n: int,
    rule: Rule = LIFE,
    interpret: bool = False,
) -> jax.Array:
    """`n` turns on a {0,255} uint8 world, whole chunk in one kernel.

    Mirrors `ops.life.step_n` (serial sweep analog,
    ref: gol/distributor.go:350-379 — done as a resident-VMEM kernel)."""
    h, w = world.shape
    bits = to_bits(world).astype(jnp.int32)
    out = pl.pallas_call(
        _make_kernel(n, rule),
        out_shape=jax.ShapeDtypeStruct((h, w), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(bits)
    return from_bits(out)


@functools.partial(jax.jit, static_argnames=("n", "rule", "interpret"))
def step_n_counted_pallas(
    world: jax.Array,
    n: int,
    rule: Rule = LIFE,
    interpret: bool = False,
):
    """`n` turns + alive count — drop-in for `ops.life.step_n_counted`;
    XLA fuses the count reduction onto the kernel's output."""
    new = step_n_pallas(world, n, rule, interpret)
    return new, jnp.sum(new != 0, dtype=jnp.int32)
