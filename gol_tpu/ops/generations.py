"""Generations (multi-state) step kernels — the B/S/C model family.

State domain: uint8 0 (dead), 1 (alive), 2..C-1 (dying). One turn
(ref semantics: the two-state reference rule is the C=2 special case
of this, ref: gol/distributor.go:325-342):

- neighbour counts see ONLY state-1 cells;
- alive stays alive iff n ∈ survive, else it starts dying (state 2,
  which for C=2 wraps straight to dead);
- dead is born iff n ∈ birth;
- dying ages by one per turn and wraps to dead at C.

Everything is a fused elementwise combine over the same separable
toroidal 3-sum as `ops/life.py` — one XLA kernel per turn, shape-
polymorphic, `jit`/sharding-safe (under a `NamedSharding` the rolls
lower to ring collectives exactly like the dense life path).

On-disk/PGM representation: states map to gray levels — 0 -> 0,
1 -> 255, dying s -> evenly spaced grays below 255 — injectively, so a
PGM snapshot is a complete checkpoint for `--resume` just like the
two-state board (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.models.rules import GenRule
from gol_tpu.ops.life import ALIVE, count_in, neighbour_counts


def step_states(state: jax.Array, rule: GenRule) -> jax.Array:
    """One Generations turn on a uint8 state grid (values 0..C-1)."""
    alive = state == 1
    n = neighbour_counts(alive.astype(jnp.uint8))
    born = (state == 0) & count_in(n, rule.birth)
    stays = alive & count_in(n, rule.survive)
    # Non-surviving alive cells and dying cells both age; age wraps to
    # dead at C (for C=2 an alive cell that fails survive dies at once).
    aged = jnp.where(state > 0, state + 1, state)
    aged = jnp.where(aged >= rule.states, 0, aged).astype(jnp.uint8)
    return jnp.where(born | stays, jnp.uint8(1), aged)


@functools.partial(jax.jit, static_argnames=("n", "rule"))
def step_n_states(state: jax.Array, n: int, rule: GenRule) -> jax.Array:
    return lax.fori_loop(0, n, lambda _, s: step_states(s, rule), state)


@functools.partial(jax.jit, static_argnames=("n", "rule"))
def step_n_counted_states(state: jax.Array, n: int, rule: GenRule):
    """`n` turns plus the alive (state-1) count, one dispatch."""
    s = step_n_states(state, n, rule)
    return s, jnp.sum(s == 1, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("rule",))
def step_with_diff_states(state: jax.Array, rule: GenRule):
    """One turn + changed-cell mask + alive count (the per-turn live
    view; 'flipped' means any state change)."""
    new = step_states(state, rule)
    return new, state != new, jnp.sum(new == 1, dtype=jnp.int32)


def levels(rule: GenRule) -> np.ndarray:
    """state -> gray level LUT: 0->0, 1->255, dying states evenly
    spaced below 255 — injective for the whole parseable range
    2 <= C <= 255 (GenRule.parse enforces the bound; the spacing
    255//C is >= 1 there and dying levels stay strictly inside
    (0, 255))."""
    lut = np.zeros(rule.states, np.uint8)
    lut[1] = ALIVE
    for s in range(2, rule.states):
        lut[s] = ALIVE - (s - 1) * (ALIVE // rule.states)
    return lut


def states_from_levels(world, rule: GenRule) -> np.ndarray:
    """Inverse of `levels` for PGM-roundtrip resume. Unknown levels
    (e.g. a plain two-state board seeding a generations run) map via
    nearest: 0 stays dead, anything else starts alive."""
    lut = levels(rule)
    world = np.asarray(world)
    out = np.zeros(world.shape, np.uint8)
    for s in range(rule.states - 1, 0, -1):
        out[world == lut[s]] = s
    out[(world != 0) & ~np.isin(world, lut)] = 1
    return out


def levels_from_states(state, rule: GenRule) -> np.ndarray:
    return levels(rule)[np.asarray(state)]
