"""Bit-packed Generations stepping — one-hot state planes, SWAR counts.

The dense generations kernel (`ops/generations.py`) spends a uint8
lane per cell. Packed form: C-1 bit-planes of 32-cells-per-uint32
words — plane 0 is the alive (state 1) mask, planes 1..C-2 are one-hot
dying-age masks. The update rule then almost vanishes:

- neighbour counts come from the SAME carry-save machinery as Life,
  run on the alive plane only (`bitlife.combine_packed`'s column-sum
  CSA, with the birth/survive masks minimized by `ops/rulecomp.py`);
- a dead cell is ``~(alive | any dying plane)``;
- aging is a PLANE RENAME: new dying plane i+1 *is* old plane i —
  zero ops — and the oldest plane wraps to dead by falling off;
- the only genuinely new work is ``new_dying[0] = alive & ~survive``.

So a C-state rule costs the Life CSA + rule combine + ~C extra bitwise
ops per word per turn — Brian's Brain runs at essentially the packed
Life rate instead of the dense one. C=2 degenerates to zero dying
planes and exactly the life-like packed step.

Like the dense family, only state-1 cells count as neighbours
(ref semantics: the reference's two-state rule is the C=2 member,
ref: gol/distributor.go:325-342). Bit-exactness vs the dense
generations kernel is asserted in tests for named and random rules.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from gol_tpu.models.rules import GenRule, Rule
from gol_tpu.ops import bitlife, rulecomp
from gol_tpu.ops.bitlife import WORD


def packable_gens(height: int, width: int) -> bool:
    del width
    return height % WORD == 0 and height >= WORD


def pack_states(state, rule: GenRule) -> "np.ndarray":
    """uint8 states (H, W) -> (C-1, H/32, W) uint32 one-hot planes."""
    import numpy as np

    state = np.asarray(state)
    return np.stack(
        [bitlife.pack_np((state == s) * np.uint8(255))
         for s in range(1, rule.states)]
    )


def unpack_states(planes, height: int, rule: GenRule) -> "np.ndarray":
    """(C-1, H/32, W) one-hot planes -> uint8 states (H, W)."""
    import numpy as np

    planes = np.asarray(planes)
    out = np.zeros((height, planes.shape[2]), np.uint8)
    for s in range(1, rule.states):
        mask = bitlife.unpack_np(planes[s - 1], height) != 0
        out[mask] = s
    return out


def _life_view(rule: GenRule) -> Rule:
    """The life-like (B/S) shadow of a generations rule — what the
    count/rule machinery sees. Cached via rulecomp's own lru on Rule."""
    return Rule(name=rule.name, birth=rule.birth, survive=rule.survive)


def step_planes(planes: tuple, rule: GenRule, up: jax.Array,
                down: jax.Array, roll=None) -> tuple:
    """One turn on a TUPLE of C-1 one-hot plane arrays, given the two
    vertically-shifted alive bitboards — the core the XLA path (below)
    and the pallas kernel (ops/pallas_bitgens.py) share; callers supply
    their shift/roll primitives exactly like bitlife.combine_packed."""
    alive = planes[0]
    plan = rulecomp.compile_rule(_life_view(rule))
    # bitlife.combine_packed fuses the masks into the two-state next
    # board, but here birth and survive feed DIFFERENT planes — so the
    # shared CSA (`rule_masks`) emits them separately.
    survive_mask, birth_mask = (
        bitlife.resolve_mask(m, alive)
        for m in bitlife.rule_masks(alive, up, down, plan, roll)
    )
    dead = ~alive
    for q in planes[1:]:
        dead = dead & ~q
    new_alive = (alive & survive_mask) | (dead & birth_mask)
    if rule.states == 2:
        return (new_alive,)
    # Aging is a plane rename; the first dying plane is the alive cells
    # that failed survive.
    return (new_alive, alive & ~survive_mask) + planes[1:-1]


def step_packed_gens(planes: jax.Array, rule: GenRule) -> jax.Array:
    """One turn on stacked (C-1, rows, W) one-hot planes (XLA path)."""
    alive = planes[0]
    new = step_planes(
        tuple(planes[i] for i in range(rule.states - 1)), rule,
        bitlife._shift_up(alive), bitlife._shift_down(alive),
    )
    return jnp.stack(new)


def step_n_packed_gens_raw(planes: jax.Array, n: int,
                           rule: GenRule) -> jax.Array:
    return lax.fori_loop(
        0, n, lambda _, q: step_packed_gens(q, rule), planes
    )


@functools.partial(jax.jit, static_argnames=("n", "rule"))
def step_n_packed_gens(planes: jax.Array, n: int, rule: GenRule):
    """`n` turns + alive count on one-hot planes, one dispatch."""
    planes = step_n_packed_gens_raw(planes, n, rule)
    return planes, bitlife.count_packed(planes[0])
