"""Lane-split (width-chunked) packed stepping — the ilp_study probe as
a library op.

PR 4's ``scripts/ilp_study.py`` proved the lane axis is a legal
interleave dimension for the packed SWAR step: split the board into k
width-chunks, ghost-extend each by ONE column from its ring neighbours,
run the plain toroidal turn on the extended chunk, and slice the
interior back out — the extended chunk's own lane wrap only corrupts
the ghost columns, which are discarded (the row-slice interleave
argument, rotated 90°). The probe lived in the bench script; the
partition layer now selects it as a named layout
(``--partition-rule layout=lane-coupled``), so the core moves here
where backends and tests can reach it. ilp_study keeps its pallas
VMEM-resident variant and imports the split from this module.

The structural cost is unchanged from the study: a W/k-lane chunk
becomes W/k + 2 lanes, never a multiple of the 128-lane vreg — so on
TPU this layout trades alignment for ILP and only wins where the study
said it does. On CPU it is bit-exact and mesh-free, which is what the
partition tests lean on.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from gol_tpu.models.rules import Rule


def lane_split_turn(chunks, turn_fn):
    """One bit-exact turn on a width-split board: each lane chunk is
    ghost-extended by ONE column from its ring-neighbour chunks, the
    plain toroidal `turn_fn` runs on the extended chunk, and the
    interior is sliced back out."""
    k = len(chunks)
    out = []
    for j in range(k):
        ext = jnp.concatenate(
            [chunks[(j - 1) % k][:, -1:], chunks[j],
             chunks[(j + 1) % k][:, :1]], axis=1,
        )
        out.append(turn_fn(ext)[:, 1:-1])
    return tuple(out)


def make_lane_coupled(rule: Rule, k: int = 2):
    """``(packed, n) -> packed`` multi-turn kernel stepping the board as
    k lane-coupled width chunks — the XLA (CPU-testable) member of the
    lane-coupled layout family; the registered entry the partition
    table's ``layout=lane-coupled`` override selects."""
    from gol_tpu.ops import bitlife

    def step_n_raw(p, n):
        if p.shape[1] % k:
            raise ValueError(
                f"lane-coupled layout needs width words divisible by "
                f"k={k}, got {p.shape[1]}"
            )
        c = p.shape[1] // k

        def turn(chunks):
            return lane_split_turn(
                chunks, lambda e: bitlife.step_packed(e, rule)
            )

        chunks = tuple(p[:, j * c:(j + 1) * c] for j in range(k))
        chunks = lax.fori_loop(0, n, lambda _, ch: turn(ch), chunks)
        return jnp.concatenate(chunks, axis=1)

    return step_n_raw
