"""Command-line entry — the analog of the reference's process entry
(ref: main.go:13-68).

Same flags, same defaults, same single-dash spelling (`-t 8 -w 512
-h 512 -turns N -noVis`, ref: main.go:17-46), plus TPU-native knobs the
Go version had no need for (--rule, --chunk, --images, --out, --tick).

Without `-noVis` the event stream drives the visualiser loop
(`gol_tpu.visual`) — a real window when a native backend is available,
otherwise a headless shadow board that still prints non-empty events the
way the SDL loop does (ref: sdl/loop.go:44-47). With `-noVis` the stream
is drained silently until `FinalTurnComplete` (ref: main.go:58-67).

Keyboard verbs p/s/q/k are forwarded from the window when visualising
(ref: sdl/loop.go:18-27) or from a raw-mode stdin reader when running
headless in a terminal — the reference has no headless key path at all.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import queue
import sys
import threading
from typing import Optional

from gol_tpu.engine.distributor import Engine
from gol_tpu.events import FinalTurnComplete
from gol_tpu.params import BACKENDS, Params


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="gol_tpu",
        description="TPU-native distributed Game of Life",
        allow_abbrev=False,
        add_help=False,  # -h is image height (ref: main.go:29-33); use --help
    )
    # Reference contract flags (single-dash, Go flag style — ref: main.go:17-46).
    ap.add_argument("-t", type=int, default=8, metavar="N",
                    help="number of worker shards (default 8)")
    ap.add_argument("-w", type=int, default=512, metavar="W",
                    help="image width (default 512)")
    ap.add_argument("-h", type=int, default=512, metavar="H",
                    help="image height (default 512)")
    ap.add_argument("-turns", type=int, default=10000000000,
                    help="turns to process (default 10000000000)")
    ap.add_argument("-noVis", action="store_true", dest="novis",
                    help="disable visualisation; drain events silently")
    ap.add_argument("--help", action="help",
                    help="show this help message and exit")
    # TPU-native extensions.
    ap.add_argument("--rule", default="B3/S23",
                    help="cellular-automaton rule in B/S notation")
    ap.add_argument("--backend", default="auto", choices=BACKENDS,
                    help="kernel family (default auto: bit-packed SWAR "
                         "when the grid allows, single-device or "
                         "sharded; pallas is single-device only)")
    ap.add_argument("--mesh", default=None, metavar="ROWSxCOLS",
                    help="force a 2-D device mesh (e.g. 2x4): the "
                         "packed board shards over word-rows AND word-"
                         "columns with mesh-generic halo exchange "
                         "(parallel/mesh2d.py); per-host halo bytes "
                         "stay flat as the column count grows. "
                         "Packed-only; exclusive with --tile")
    ap.add_argument("--partition-rule", default=None, dest="partition_rule",
                    metavar="RULES",
                    help="partition-table overrides, prepended to the "
                         "backend family's defaults (first match wins): "
                         "'PATTERN=AXES;...' with AXES a comma list of "
                         "rows/cols/* or '-' for replicated, plus "
                         "'layout=NAME' to select a registered kernel "
                         "layout (e.g. layout=lane-coupled). See "
                         "docs/PERF.md '2D mesh sharding'")
    ap.add_argument("--chunk", type=int, default=None, metavar="K",
                    help="turns fused per device dispatch when no per-turn "
                         "consumer is attached; 0 auto-calibrates to ~0.1s "
                         "per dispatch (default: 1 visualising, auto "
                         "headless)")
    ap.add_argument("--images", default="images", metavar="DIR",
                    help="input image directory (default images/)")
    ap.add_argument("--out", default="out", metavar="DIR",
                    help="output image directory (default out/)")
    ap.add_argument("--tick", type=float, default=2.0, metavar="SEC",
                    help="AliveCellsCount cadence in seconds (default 2)")
    ap.add_argument("--autosave-turns", type=int, default=0, metavar="N",
                    help="auto-checkpoint the board to out/ every N "
                         "completed turns (0 = off)")
    ap.add_argument("--autosave-secs", type=float, default=0.0,
                    metavar="SEC",
                    help="auto-checkpoint the board to out/ every SEC "
                         "seconds (0 = off)")
    ap.add_argument("--tile", type=int, default=0, metavar="T",
                    help="activity-driven tiled stepping: split the "
                         "board into T x T macro-tiles (T a multiple "
                         "of 32 dividing both axes) and dispatch only "
                         "tiles a change's light cone touched; the "
                         "board stays host-resident, so size stops "
                         "being an HBM bound (0 = off; -t does not "
                         "apply — the dispatch set is the parallelism; "
                         "see docs/PERF.md 'Activity-driven stepping')")
    ap.add_argument("--cycle-detect", action="store_true",
                    dest="cycle_detect",
                    help="exact cycle fast-forward: once the board "
                         "provably revisits a state, collapse the "
                         "remaining turns modulo the period (bit-exact; "
                         "makes the 10^10-turn default run finish). "
                         "Only active on headless fused runs: pass "
                         "-noVis, and detach any live controller")
    ap.add_argument("--metrics-port", type=int, default=None,
                    dest="metrics_port", metavar="PORT",
                    help="serve live observability on "
                         "127.0.0.1:PORT — /metrics (Prometheus text), "
                         "/vars (JSON snapshot), /healthz (liveness); "
                         "0 picks an ephemeral port (printed). Works "
                         "for local engines, --serve and --connect; "
                         "see docs/OBSERVABILITY.md")
    ap.add_argument("--metrics-host", default="127.0.0.1", metavar="HOST",
                    help="bind address for --metrics-port (default "
                         "loopback; non-loopback exposure should sit "
                         "behind the same controls as --serve)")
    ap.add_argument("--alert-rules", default=None, dest="alert_rules",
                    metavar="FILE",
                    help="with --metrics-port: SLO alert rules evaluated "
                         "inside the sidecar (gol_tpu.obs.freshness), "
                         "one per line, e.g. 'age: p99(gol_tpu_server_"
                         "turn_age_seconds) > 2 for 30s'; state served "
                         "at /alerts, transitions counted and noted in "
                         "the flight recorder; a parse error is a "
                         "STARTUP error, never a runtime crash")
    ap.add_argument("--remote-write", default=None, dest="remote_write",
                    metavar="HOST:PORT",
                    help="with --metrics-port: push this sidecar's "
                         "registry (plus alert transitions and span "
                         "digests) to the history-plane collector at "
                         "HOST:PORT — delta-encoded sample frames on "
                         "the framed wire, client deadlines + jittered "
                         "backoff per link; a slow or dead collector "
                         "SHEDS samples, never wedges this process "
                         "(docs/OBSERVABILITY.md 'History plane')")
    ap.add_argument("--collector", default=None, metavar="[HOST:]PORT",
                    help="run as the HISTORY-PLANE COLLECTOR "
                         "(gol_tpu.obs.collector): ingest --remote-"
                         "write telemetry into crash-atomic segment "
                         "logs under <out>/tsdb and serve range "
                         "queries (/query, /history) from its own "
                         "--metrics-port sidecar; --resume latest "
                         "replays the store to the last good sample; "
                         "--alert-rules evaluate FLEET-WIDE over "
                         "collected series with for: durations judged "
                         "against history")
    ap.add_argument("--session-budget-flops", type=float, default=None,
                    dest="session_budget_flops", metavar="FLOPS",
                    help="with --serve --sessions: soft per-tenant "
                         "modeled-FLOPs budget (accounting plane, "
                         "docs/OBSERVABILITY.md) — over-budget tenants "
                         "raise gol_tpu_usage_over_budget (alert-rule "
                         "food) and show BUDG=OVER in obs.console; "
                         "deliberately never enforced")
    ap.add_argument("--session-budget-bytes", type=float, default=None,
                    dest="session_budget_bytes", metavar="BYTES",
                    help="with --serve --sessions: soft per-tenant "
                         "wire-bytes budget — same advisory semantics "
                         "as --session-budget-flops")
    ap.add_argument("--profile-dir", default=None, dest="profile_dir",
                    metavar="DIR",
                    help="capture a jax.profiler device trace into DIR "
                         "for the whole run (opt-in: profiling taxes "
                         "the dispatch path) — the capture directory "
                         "is linked from the span tracer's export so "
                         "obs.report merge names it next to the "
                         "host-side timeline; see docs/OBSERVABILITY.md "
                         "'Device plane'")
    ap.add_argument("--check-invariants", action="store_true",
                    dest="check_invariants",
                    help="assert distributed-protocol invariants at "
                         "runtime (event-stream ordering, dispatch "
                         "linearity — gol_tpu.analysis.invariants); "
                         "cheap host-side identity checks, also "
                         "switchable via GOL_TPU_CHECK_INVARIANTS=1")
    ap.add_argument("--platform", default=None, metavar="NAME",
                    help="force a jax platform (e.g. cpu, tpu); some "
                         "site configs pin the platform so the "
                         "JAX_PLATFORMS env var alone is ignored")
    # Distributed split (the working version of the reference's intended
    # controller ⇄ engine topology, ref: README.md:157-233).
    ap.add_argument("--serve", default=None, metavar="[HOST:]PORT",
                    help="run as a headless engine server on this address")
    ap.add_argument("--sessions", action="store_true",
                    help="with --serve: multi-tenant session mode "
                         "(gol_tpu.sessions) — no singleton board; "
                         "peers create/destroy/checkpoint named "
                         "sessions over the wire and attach with "
                         "hello.session; same-shape sessions share one "
                         "vmapped dispatch. -w/-h set the geometry "
                         "CAP for wire-driven creates' sanity bound "
                         "only; see docs/SESSIONS.md")
    ap.add_argument("--bucket-capacity", type=int, default=16,
                    dest="bucket_capacity", metavar="S",
                    help="with --sessions: initial slots per "
                         "shape/rule bucket (a full bucket doubles, "
                         "which recompiles; churn within capacity "
                         "never does; default 16)")
    ap.add_argument("--park-idle-secs", type=float, default=None,
                    dest="park_idle_secs", metavar="SEC",
                    help="with --serve --sessions: HIBERNATE sessions "
                         "idle (no watcher, no driver) this long — "
                         "checkpoint via the session manifest, free "
                         "the device slot, rehydrate bit-exactly on "
                         "the next attach; 0 parks at the first idle "
                         "sweep (default: never park; see "
                         "docs/SESSIONS.md 'Hibernation')")
    ap.add_argument("--record", action="store_true",
                    help="with --serve --sessions: tape every "
                         "session's encoded wire stream (FBATCH "
                         "frames + periodic BoardSync keyframes, "
                         "verbatim bytes) into an append-only segment "
                         "log under out/sessions/<id>/replay/ — the "
                         "seekable recording the seek verb and "
                         "--replay serve from (docs/REPLAY.md)")
    ap.add_argument("--keyframe-turns", type=int, default=None,
                    dest="keyframe_turns", metavar="N",
                    help="with --record: turns between BoardSync "
                         "keyframes = seek granularity and per-attach "
                         "catch-up cost (default 256)")
    ap.add_argument("--record-max-bytes", type=int, default=None,
                    dest="record_max_bytes", metavar="BYTES",
                    help="with --record: per-session recording size "
                         "bound — oldest segments are evicted past it "
                         "(default: unbounded)")
    ap.add_argument("--replay", default=None, metavar="LOG-DIR",
                    dest="replay",
                    help="run as a STATIC REPLAY SERVER "
                         "(gol_tpu.replay): serve the recordings "
                         "under LOG-DIR (a --record run's "
                         "out/sessions tree, one session's dir, or a "
                         "bare replay/ dir) on --serve [HOST:]PORT to "
                         "any number of observers with ZERO engine "
                         "dispatches — recorded bytes forwarded "
                         "verbatim, paced by the recorded timestamps "
                         "or --replay-rate; composes under --relay "
                         "trees (docs/REPLAY.md)")
    ap.add_argument("--replay-rate", type=float, default=None,
                    dest="replay_rate", metavar="TURNS/S",
                    help="with --replay: playback pacing in turns/s "
                         "(0 = as fast as the observers drain; "
                         "default: the recorded wall-clock timing)")
    ap.add_argument("--relay", default=None, metavar="HOST:PORT",
                    help="run as a RELAY NODE (gol_tpu.relay): attach "
                         "to the upstream server/relay at HOST:PORT as "
                         "one batching binary client and re-serve its "
                         "stream on --serve [HOST:]PORT to any number "
                         "of observers, forwarding identical frame "
                         "bytes with zero re-encode; reconnect and "
                         "clock sync compose per hop (docs/RELAY.md)")
    ap.add_argument("--ws-port", type=int, default=None,
                    dest="ws_port", metavar="PORT",
                    help="with --relay: also serve browser observers "
                         "over RFC-6455 WebSocket on this port — the "
                         "identical binary frames inside WS binary "
                         "messages (subprotocol gol-tpu-wire)")
    ap.add_argument("--writer-pool-threads", type=int, default=2,
                    dest="writer_pool_threads", metavar="N",
                    help="with --serve/--relay: selector event-loop "
                         "threads draining every peer's outbound "
                         "frames (thousands of sockets per thread; "
                         "default 2, 0 restores a writer thread per "
                         "connection)")
    ap.add_argument("--control", default=None, metavar="SPEC.json",
                    help="run as the FLEET CONTROLLER (gol_tpu.control): "
                         "own the declarative topology in SPEC.json and "
                         "reconcile observed state toward it — heal dead "
                         "relays (spawn + re-point the orphaned subtree), "
                         "grow/shrink the relay tree, migrate sessions "
                         "bit-exactly between engines, and roll managed "
                         "engines behind --resume latest "
                         "(docs/CONTROL.md)")
    ap.add_argument("--connect", default=None, metavar="HOST:PORT",
                    help="run as a controller attached to a remote engine")
    ap.add_argument("--session", default=None, metavar="ID",
                    help="with --connect: watch/drive the named session "
                         "on a --serve --sessions server instead of the "
                         "singleton board (docs/SESSIONS.md)")
    ap.add_argument("--observe", action="store_true",
                    help="with --connect: attach read-only (board sync "
                         "+ events; steering verbs rejected) — any "
                         "number of observers may watch alongside the "
                         "one driving controller")
    ap.add_argument("--secret", default=os.environ.get("GOL_SECRET"),
                    metavar="TOKEN",
                    help="shared secret for --serve/--connect: a serving "
                         "engine rejects attaches whose hello carries a "
                         "different token (defaults to $GOL_SECRET; unset "
                         "means unauthenticated)")
    ap.add_argument("--resume", default=None, metavar="SNAPSHOT.pgm",
                    help="resume from an out/ snapshot, continuing at "
                         "the turn encoded in its filename; 'latest' "
                         "picks the newest matching snapshot in --out")
    # Resilience knobs (docs/RESILIENCE.md).
    ap.add_argument("--hb-secs", type=float, default=2.0, metavar="SEC",
                    dest="hb_secs",
                    help="with --serve: heartbeat cadence into idle "
                         "peer streams; silent heartbeat-capable peers "
                         "are evicted after --evict-secs (0 disables "
                         "the liveness plane; default 2)")
    ap.add_argument("--evict-secs", type=float, default=None,
                    metavar="SEC", dest="evict_secs",
                    help="with --serve: idle-eviction deadline for "
                         "peers that stop answering heartbeats "
                         "(default 3x --hb-secs)")
    # Overload knobs (docs/RESILIENCE.md "Overload & degradation").
    ap.add_argument("--max-peers", type=int, default=None,
                    dest="max_peers", metavar="N",
                    help="with --serve: admission budget — attaches "
                         "past N live peers are rejected "
                         "'at-capacity' with a retry_after hint "
                         "(default: unbounded)")
    ap.add_argument("--max-sessions", type=int, default=None,
                    dest="max_sessions", metavar="N",
                    help="with --serve --sessions: creates past N "
                         "live sessions are rejected 'max-sessions' "
                         "with a retry_after hint (default: "
                         "unbounded)")
    ap.add_argument("--high-water", type=int, default=None,
                    dest="high_water", metavar="FRAMES",
                    help="with --serve: writer-queue depth at which a "
                         "slow peer is DEGRADED (stream frames shed, "
                         "coalesced BoardSync on drain) instead of "
                         "evicted (default 256)")
    ap.add_argument("--drain-secs", type=float, default=None,
                    dest="drain_secs", metavar="SEC",
                    help="with --serve: how long a degraded peer may "
                         "stay wedged before eviction — peers that "
                         "drain inside the deadline are resynced and "
                         "keep watching (default 10)")
    ap.add_argument("--batch-turns", type=int, default=None,
                    dest="batch_turns", metavar="K",
                    help="with --serve: ceiling on a peer's hello "
                         "\"batch\" max-k (turns per flip-batch wire "
                         "frame; default 1024, 0 disables batching). "
                         "With --connect: request k-turn batch frames "
                         "— the watched-path throughput mode "
                         "(docs/PERF.md \"Batched wire turns\")")
    ap.add_argument("--no-reconnect", action="store_true",
                    dest="no_reconnect",
                    help="with --connect: die on the first link "
                         "failure instead of re-dialing with backoff "
                         "and resuming via board sync")
    ap.add_argument("--reconnect-secs", type=float, default=60.0,
                    metavar="SEC", dest="reconnect_secs",
                    help="with --connect: total re-dial window after a "
                         "link failure — long enough to ride out a "
                         "server crash-restart with --resume "
                         "(default 60)")
    # Multi-host SPMD job membership (parallel/multihost.py). All three
    # default to the JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    # JAX_PROCESS_ID env vars; unset means single-process.
    ap.add_argument("--mh-coordinator", default=None, metavar="HOST:PORT",
                    help="jax.distributed coordinator address; non-zero "
                         "process ids run as SPMD workers mirroring the "
                         "coordinator's dispatches")
    ap.add_argument("--mh-procs", type=int, default=None, metavar="N",
                    help="total process count in the multi-host job")
    ap.add_argument("--mh-id", type=int, default=None, metavar="I",
                    help="this process's id (0 = coordinator)")
    return ap


def _start_metrics(args, health=None, tsdb=None, series_source=None):
    """Opt-in observability sidecar (gol_tpu.obs.http): serve the
    process registry + a health probe whenever --metrics-port is given.
    With --alert-rules, the freshness plane's SLO evaluator runs
    inside the sidecar (served at /alerts) — rule-file parse errors
    abort AT STARTUP with the offending line, so a typo can never take
    a serving process down at runtime. With --remote-write, a
    history-plane RemoteWriter rides the sidecar too, pushing this
    registry to the collector. Returns the MetricsServer (caller
    closes it — evaluator and writer ride its lifecycle) or None."""
    if getattr(args, "alert_rules", None) is not None \
            and args.metrics_port is None:
        raise SystemExit(
            "error: --alert-rules requires --metrics-port (the "
            "evaluator runs inside the metrics sidecar)"
        )
    if getattr(args, "remote_write", None) is not None \
            and args.metrics_port is None:
        raise SystemExit(
            "error: --remote-write requires --metrics-port (the "
            "writer rides the metrics sidecar, and the sidecar "
            "address is its source label)"
        )
    if args.metrics_port is None:
        return None
    from gol_tpu.obs.http import MetricsServer

    alerts = None
    if getattr(args, "alert_rules", None) is not None:
        from gol_tpu.obs.freshness import AlertEvaluator, load_rules

        try:
            rules = load_rules(args.alert_rules)
        except OSError as e:
            raise SystemExit(f"error: cannot read --alert-rules: {e}") \
                from None
        except ValueError as e:
            raise SystemExit(f"error: {e}") from None
        alerts = AlertEvaluator(rules, series_source=series_source)
        print(f"alert evaluator armed: {len(rules)} rule(s) from "
              f"{args.alert_rules}")
    srv = MetricsServer(args.metrics_host, args.metrics_port,
                        health=health, alerts=alerts, tsdb=tsdb)
    if getattr(args, "remote_write", None) is not None:
        from gol_tpu.obs.collector import RemoteWriter

        # The sidecar's own bound address is the source label: it is
        # unique per process on a host and is exactly how the console
        # and the controller already name this endpoint.
        srv.remote = RemoteWriter(
            args.remote_write,
            source=f"{srv.address[0]}:{srv.address[1]}",
            alerts=alerts, secret=args.secret,
        )
        print(f"remote-write to {args.remote_write} "
              f"(source {srv.remote.source})")
    srv.start()
    print(f"metrics serving on http://{srv.address[0]}:{srv.address[1]}"
          "/metrics")
    return srv


def _stdin_keys(keypresses: queue.Queue, stop: threading.Event) -> None:
    """Stdin reader forwarding the p/s/q/k verbs. The terminal mode is
    owned by main() — this daemon thread can be frozen mid-read at
    interpreter exit, so it must not be the one holding the restore."""
    while not stop.is_set():
        ch = sys.stdin.read(1)
        if ch in ("p", "s", "q", "k"):
            keypresses.put(ch)
        if ch in ("q", "k") or not ch:
            return


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.check_invariants:
        # Env-var form on purpose: multi-host worker processes and
        # spawned tools inherit the opt-in with the environment.
        from gol_tpu.analysis.invariants import enable

        enable()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    # Join (or create) the multi-host job before anything touches the
    # backend; a no-op unless flags/env vars name a coordinator.
    from gol_tpu.parallel import multihost

    try:
        multihost.initialize(args.mh_coordinator, args.mh_procs, args.mh_id)
    except ValueError as e:
        raise SystemExit(f"error: {e}") from None
    import jax

    # A flag mismatch between job processes would build divergent SPMD
    # programs that deadlock at the first collective; fail fast instead.
    multihost.verify_job_config(
        args.w, args.h, args.t, args.rule, args.backend,
        args.mesh, args.partition_rule,
    )

    if jax.process_count() > 1 and not multihost.is_coordinator():
        # Worker process: no IO, no events, no server — just mirror the
        # coordinator's dispatches over the global mesh until released.
        from gol_tpu.parallel.stepper import make_stepper

        s = make_stepper(threads=args.t, height=args.h, width=args.w,
                         rule=args.rule, backend=args.backend,
                         mesh=args.mesh,
                         partition_rules=args.partition_rule)
        multihost.spmd_worker_loop(s, args.h, args.w)
        return 0

    # Observability bootstrap (docs/OBSERVABILITY.md): label this
    # process for merged timelines, arm the flight recorder's dump
    # directory (--out — where the checkpoints already live), and dump
    # the black box the instant SIGTERM lands (the handler then raises
    # KeyboardInterrupt, so every mode's ordinary graceful-shutdown
    # path still runs). All no-ops under GOL_TPU_METRICS=0.
    from gol_tpu.obs import device, flight, tracing

    tracing.set_process_label(
        "control" if args.control is not None
        else "collector" if args.collector is not None
        else "replay" if args.replay is not None
        else "serve" if args.serve is not None
        else "connect" if args.connect is not None else "local"
    )
    flight.configure(args.out)
    flight.install_sigterm_handler()
    # Device plane (docs/OBSERVABILITY.md "Device plane"): every real
    # run watches its compiles and publishes its programs' cost model;
    # library embedders opt in explicitly (a cost probe is one small
    # AOT compile per engine). --profile-dir drives jax.profiler and
    # stops it at exit (atexit inside start_profile).
    device.install_compile_watcher()
    device.enable_cost_probes()
    # Accounting plane (docs/OBSERVABILITY.md "Accounting plane"):
    # engines and serving tiers keep a crash-safe usage ledger under
    # <out>/usage and honor the soft budgets; a --connect controller
    # spends on the server's bill, not its own. All no-ops under
    # GOL_TPU_ACCOUNTING=0 (zero ledger I/O).
    from gol_tpu.obs import accounting

    if args.connect is None:
        accounting.configure(
            out_dir=args.out,
            budget_flops=args.session_budget_flops,
            budget_bytes=args.session_budget_bytes,
        )
    if args.profile_dir:
        if device.start_profile(args.profile_dir):
            print(f"jax profiler capturing to {args.profile_dir}")
        else:
            print("warning: jax profiler capture could not start "
                  f"in {args.profile_dir}", file=sys.stderr)

    # Banner (ref: main.go:48-50).
    print("Threads:", args.t)
    print("Width:", args.w)
    print("Height:", args.h)

    # Multi-state rules visualise as gray levels (r5): the board runs
    # in level mode and flip batches carry per-cell levels — no more
    # forced-headless carve-out for the Generations family.
    from gol_tpu.models.rules import GenRule, get_rule
    try:
        rule_obj = get_rule(args.rule)
    except ValueError as e:
        raise SystemExit(f"error: {e}") from None
    vis_levels = isinstance(rule_obj, GenRule)

    # All engines default to chunk 0 (no cap): headless runs
    # auto-calibrate their fused dispatches, and a local visualiser
    # rides the device-accumulated diff path, which self-chunks
    # (engine DIFF_CHUNK) — an explicit --chunk bounds both.
    chunk = args.chunk if args.chunk is not None else 0
    params = Params(
        turns=args.turns,
        threads=args.t,
        image_width=args.w,
        image_height=args.h,
        rule=rule_obj,
        backend=args.backend,
        chunk=chunk,
        tick_seconds=args.tick,
        image_dir=args.images,
        out_dir=args.out,
        autosave_turns=args.autosave_turns,
        autosave_seconds=args.autosave_secs,
        cycle_detect=args.cycle_detect,
        tile=args.tile,
        mesh=args.mesh,
        partition_rules=args.partition_rule,
    )

    # Checkpoint restart (local or --serve): boot from a snapshot,
    # continuing at the turn in its filename (SURVEY.md §5
    # checkpoint/resume). A controller holds no board state, so
    # --connect cannot resume — the engine server is where state lives.
    resume_path = args.resume
    if resume_path is not None and args.connect is not None:
        raise SystemExit(
            "error: --resume applies to the engine (local or --serve), "
            "not to a --connect controller"
        )
    if args.session is not None and args.connect is None \
            and args.relay is None:
        raise SystemExit("error: --session requires --connect "
                         "(or --relay, to fan a named session out)")
    if args.relay is not None and args.sessions:
        raise SystemExit(
            "error: --relay attaches to a session server with "
            "--session ID; --sessions starts one"
        )
    if args.ws_port is not None and args.relay is None:
        # Before ANY serve-mode dispatch: a silently ignored WS port
        # would leave an operator believing browsers are served.
        raise SystemExit(
            "error: --ws-port requires --relay (a root engine serves "
            "browsers through a co-located relay: start one with "
            "--relay HOST:PORT --serve PORT --ws-port N)"
        )
    if args.collector is not None:
        # The history-plane collector is its own process mode: it
        # stores telemetry ABOUT serving processes rather than being
        # one, and --resume latest replays its own segment logs.
        if (args.serve is not None or args.sessions
                or args.relay is not None or args.connect is not None
                or args.replay is not None or args.control is not None):
            raise SystemExit(
                "error: --collector is its own mode — it cannot "
                "combine with --serve/--sessions/--relay/--connect/"
                "--replay/--control"
            )
        if resume_path not in (None, "latest"):
            raise SystemExit(
                "error: a collector resumes its own segment logs "
                "under <out>/tsdb; use --resume latest (or none)"
            )
        return _collector(args, resume_path == "latest")
    if args.remote_write is not None and args.metrics_port is None:
        # Before ANY mode dispatch: a silently ignored remote-write
        # target would leave an operator believing telemetry is
        # being collected.
        raise SystemExit(
            "error: --remote-write requires --metrics-port (the "
            "writer rides the metrics sidecar, and the sidecar "
            "address is its source label)"
        )
    if args.control is not None:
        # The fleet controller is its own process mode: it OWNS serving
        # processes rather than being one, and it applies --resume
        # latest to the engines it rolls, never to itself.
        if (args.serve is not None or args.sessions
                or args.relay is not None or args.connect is not None
                or args.replay is not None):
            raise SystemExit(
                "error: --control is its own mode — it cannot combine "
                "with --serve/--sessions/--relay/--connect/--replay"
            )
        if resume_path is not None:
            raise SystemExit(
                "error: --resume applies to an engine; the controller "
                "itself holds no board state (it rolls engines with "
                "--resume latest on their behalf)"
            )
        return _control_plane(args)
    if args.park_idle_secs is not None and not args.sessions:
        raise SystemExit(
            "error: --park-idle-secs applies to --serve --sessions "
            "(hibernation is a session-plane policy)"
        )
    if (args.session_budget_flops is not None
            or args.session_budget_bytes is not None) \
            and not args.sessions:
        # A silently ignored budget would leave an operator believing
        # tenants are being watched.
        raise SystemExit(
            "error: --session-budget-flops/--session-budget-bytes "
            "apply to --serve --sessions (per-tenant accounting)"
        )
    if args.record and not args.sessions:
        raise SystemExit(
            "error: --record applies to --serve --sessions (the "
            "replay log is a session-plane recording; docs/REPLAY.md)"
        )
    if not args.record and (args.keyframe_turns is not None
                            or args.record_max_bytes is not None):
        # A silently ignored recording knob would leave an operator
        # believing a cadence/bound is in force.
        raise SystemExit(
            "error: --keyframe-turns/--record-max-bytes require "
            "--record"
        )
    if args.replay_rate is not None and args.replay is None:
        raise SystemExit("error: --replay-rate requires --replay")
    if args.replay is not None:
        if args.sessions or args.relay is not None \
                or args.connect is not None:
            raise SystemExit(
                "error: --replay is its own serving mode — it cannot "
                "combine with --sessions/--relay/--connect"
            )
        if args.tile:
            # Same reasoning as the --tile guard below: a replay
            # server owns no board to tile.
            raise SystemExit(
                "error: --tile applies to single-board engines, not "
                "a replay server"
            )
        if args.serve is None:
            raise SystemExit(
                "error: --replay needs --serve [HOST:]PORT for its "
                "listener"
            )
        if resume_path is not None:
            raise SystemExit(
                "error: --resume applies to an engine, not a replay "
                "server"
            )
        return _replay_serve(args)
    if args.tile and (args.sessions or args.relay is not None):
        # Buckets step dense stacks and relays own no board: a
        # silently ignored --tile would leave an operator believing a
        # 32k-scale geometry runs activity-driven when it would OOM
        # or run dense.
        raise SystemExit(
            "error: --tile applies to single-board engines (local or "
            "--serve), not --sessions buckets or relays"
        )
    if args.sessions:
        # Multi-tenant serve mode: state lives per session under
        # out/sessions/, so the singleton snapshot discovery below
        # does not apply — resume means "restore every session".
        if args.serve is None:
            raise SystemExit("error: --sessions requires --serve")
        if resume_path not in (None, "latest"):
            raise SystemExit(
                "error: --sessions resumes per-session checkpoints; "
                "use --resume latest (or none)"
            )
        return _serve_sessions(args, params, resume_path == "latest")
    if args.relay is not None:
        # Relay node: no engine of its own — resume/snapshot flags
        # make no sense here, and the downstream address is --serve.
        if args.serve is None:
            raise SystemExit(
                "error: --relay needs --serve [HOST:]PORT for its "
                "downstream listener"
            )
        if resume_path is not None:
            raise SystemExit(
                "error: --resume applies to an engine, not a relay"
            )
        return _relay(args)
    if resume_path == "latest":
        from gol_tpu.checkpoint import latest_snapshot

        resume_path = latest_snapshot(args.out, args.w, args.h)
        if resume_path is None:
            raise SystemExit(
                f"error: no {args.w}x{args.h} snapshot found in {args.out}/"
            )
    resume_turn = 0
    if resume_path is not None:
        from gol_tpu.checkpoint import snapshot_turn

        try:
            resume_turn = snapshot_turn(resume_path)
        except ValueError as e:
            raise SystemExit(
                f"error: {e} — snapshots are named <W>x<H>x<TURN>.pgm"
            ) from None
        if resume_turn > args.turns:
            raise SystemExit(
                f"error: snapshot is at turn {resume_turn}, beyond "
                f"-turns {args.turns}"
            )

    if args.serve is not None:
        return _serve(args, params, resume_path)

    keypresses: queue.Queue = queue.Queue()
    stop_keys = threading.Event()
    saved_termios = None
    if sys.stdin.isatty():
        import termios
        import tty

        saved_termios = termios.tcgetattr(sys.stdin.fileno())
        tty.setcbreak(sys.stdin.fileno())
        threading.Thread(
            target=_stdin_keys, args=(keypresses, stop_keys),
            name="gol-keys", daemon=True,
        ).start()

    try:
        if args.connect is not None:
            return _control(args, params, keypresses)

        engine_kwargs = {}
        if resume_path is not None:
            from gol_tpu.checkpoint import record_resume_turn
            from gol_tpu.io.pgm import read_pgm

            engine_kwargs = {
                "initial_world": read_pgm(resume_path),
                "start_turn": resume_turn,
            }
            record_resume_turn(resume_turn)
        # Per-turn CellFlipped diffs only matter when something consumes them.
        if params.cycle_detect and not args.novis:
            print("warning: --cycle-detect only engages on headless "
                  "fused runs; pass -noVis for it to fire",
                  file=sys.stderr)
        # The built-in visualiser applies flips vectorized, so the local
        # watched run uses per-turn FlipBatch arrays (library consumers
        # of gol_tpu.run() keep the per-cell reference contract).
        engine = Engine(params, keypresses=keypresses,
                        emit_flips=not args.novis,
                        emit_flip_batches=not args.novis, **engine_kwargs)
        # Sidecar BEFORE the engine thread: a failed port bind aborts a
        # run that hasn't started anything needing cleanup yet.
        metrics = _start_metrics(args, health=engine.health)
        from gol_tpu.obs import flight as _flight

        _flight.set_state_provider(engine.health)
        engine.start()
        try:
            if args.novis:
                # Silent drain until the final turn (ref: main.go:58-67).
                for ev in engine.events:
                    if isinstance(ev, FinalTurnComplete):
                        break
            else:
                from gol_tpu.visual import run_loop

                run_loop(params, engine.events, keypresses,
                         levels=vis_levels)
        except KeyboardInterrupt:
            keypresses.put("q")
        finally:
            engine.join(timeout=60)
            if metrics is not None:
                metrics.close()

        if engine.error is not None:
            print(f"engine error: {engine.error!r}", file=sys.stderr)
            return 1
        if engine.skipped_turns:
            print(f"cycle fast-forward: skipped {engine.skipped_turns} "
                  "turns (proven state revisit; result is bit-exact)")
        return 0
    finally:
        # On an exception path, skip releasing the workers: errors from
        # config-identical code (e.g. stepper validation) raised on them
        # too, and broadcasting to dead peers blocks forever — hiding
        # the coordinator's own traceback. The distributed runtime tears
        # down workers of an exited coordinator instead.
        if sys.exc_info()[0] is None:
            multihost.notify_stop()
        stop_keys.set()
        if saved_termios is not None:
            import termios

            termios.tcsetattr(sys.stdin.fileno(), termios.TCSADRAIN, saved_termios)


def _addr(spec: str, default_host: str = "127.0.0.1") -> tuple[str, int]:
    host, _, port = spec.rpartition(":")
    try:
        return (host or default_host, int(port))
    except ValueError:
        raise SystemExit(
            f"error: bad address {spec!r} — expected [HOST:]PORT"
        ) from None


def _serve(args, params: Params, resume_path: Optional[str] = None) -> int:
    """Headless engine server (the reference's AWS-side node,
    ref: README.md:157-175).

    Binds loopback unless an explicit HOST is given, and --secret (or
    $GOL_SECRET) authenticates attaches — without it any peer that can
    connect may pull board state or send the 'k' kill verb, so non-
    loopback exposure should pair `--serve 0.0.0.0:8030` with a
    secret."""
    from gol_tpu.distributed import EngineServer

    host, port = _addr(args.serve, default_host="127.0.0.1")
    server = EngineServer(params, host, port, resume_from=resume_path,
                          secret=args.secret,
                          heartbeat_secs=args.hb_secs,
                          evict_secs=args.evict_secs,
                          max_peers=args.max_peers,
                          high_water=args.high_water,
                          drain_secs=args.drain_secs,
                          batch_turns=(args.batch_turns
                                       if args.batch_turns is not None
                                       else 1024),
                          writer_pool_threads=args.writer_pool_threads)
    print(f"engine serving on {server.address[0]}:{server.address[1]}")
    # Sidecar BEFORE the engine/broadcast threads: a failed port bind
    # aborts while nothing needing teardown is running (a bind failure
    # after start would skip the shutdown path and strand multi-host
    # workers waiting for their next opcode).
    metrics = _start_metrics(args, health=server.health)
    from gol_tpu.obs import flight as _flight

    _flight.set_state_provider(server.health)
    server.start()
    try:
        while not server.wait(timeout=1.0):
            pass
    except KeyboardInterrupt:
        server.shutdown()
    finally:
        from gol_tpu.parallel import multihost

        multihost.notify_stop()
        if metrics is not None:
            metrics.close()
    if server.engine.error is not None:
        print(f"engine error: {server.engine.error!r}", file=sys.stderr)
        return 1
    return 0


def _serve_sessions(args, params: Params, resume: bool) -> int:
    """Multi-tenant session server (gol_tpu.sessions; the
    `--serve --sessions` mode — docs/SESSIONS.md). Same exposure rules
    as --serve: loopback unless an explicit HOST, --secret gates every
    attach AND every session verb."""
    from gol_tpu.distributed import SessionServer

    host, port = _addr(args.serve, default_host="127.0.0.1")
    server = SessionServer(params, host, port, secret=args.secret,
                           heartbeat_secs=args.hb_secs,
                           evict_secs=args.evict_secs,
                           resume=resume,
                           bucket_capacity=args.bucket_capacity,
                           max_peers=args.max_peers,
                           max_sessions=args.max_sessions,
                           high_water=args.high_water,
                           drain_secs=args.drain_secs,
                           batch_turns=(args.batch_turns
                                        if args.batch_turns is not None
                                        else 1024),
                           writer_pool_threads=args.writer_pool_threads,
                           park_idle_secs=args.park_idle_secs,
                           record=args.record,
                           keyframe_turns=(args.keyframe_turns
                                           if args.keyframe_turns
                                           is not None else 256),
                           record_max_bytes=args.record_max_bytes)
    print(f"session engine serving on "
          f"{server.address[0]}:{server.address[1]}")
    if resume:
        print(f"resumed {server.resumed} session(s) from "
              f"{params.out_dir}/sessions/")
    metrics = _start_metrics(args, health=server.health)
    from gol_tpu.obs import flight as _flight

    _flight.set_state_provider(server.health)
    server.start()
    try:
        while not server.wait(timeout=1.0):
            if not server.engine.running():
                # A fatal dispatch-loop error must take the server
                # down with it — otherwise the listener keeps
                # accepting onto a dead engine and the error report
                # below is unreachable.
                server.shutdown()
    except KeyboardInterrupt:
        server.shutdown()
    finally:
        if metrics is not None:
            metrics.close()
    if server.engine.error is not None:
        print(f"session engine error: {server.engine.error!r}",
              file=sys.stderr)
        return 1
    return 0


def _replay_serve(args) -> int:
    """Static replay server (gol_tpu.replay; docs/REPLAY.md): serve
    the recordings under --replay LOG-DIR with zero engine dispatches.
    Same exposure rules as --serve: loopback unless an explicit HOST,
    --secret authenticates every attach."""
    from gol_tpu.replay import ReplayServer

    host, port = _addr(args.serve, default_host="127.0.0.1")
    try:
        server = ReplayServer(
            args.replay, host, port,
            secret=args.secret,
            replay_rate=args.replay_rate,
            heartbeat_secs=args.hb_secs,
            evict_secs=args.evict_secs,
            max_peers=args.max_peers,
            high_water=args.high_water,
            drain_secs=args.drain_secs,
            batch_turns=(args.batch_turns
                         if args.batch_turns is not None else 1024),
            writer_pool_threads=args.writer_pool_threads,
        )
    except ValueError as e:
        raise SystemExit(f"error: {e}") from None
    n = len(server._recordings)
    print(f"replay serving on {server.address[0]}:{server.address[1]} "
          f"({n} recording{'s' if n != 1 else ''} from {args.replay})")
    metrics = _start_metrics(args, health=server.health)
    from gol_tpu.obs import flight as _flight

    _flight.set_state_provider(server.health)
    server.start()
    try:
        while not server.wait(timeout=1.0):
            pass
    except KeyboardInterrupt:
        server.shutdown()
    finally:
        if metrics is not None:
            metrics.close()
    return 0


def _relay(args) -> int:
    """Relay node (gol_tpu.relay; docs/RELAY.md): attach upstream as
    one batching binary client, re-serve the stream to N observers
    (TCP on --serve, browsers on --ws-port) with zero re-encode.
    Same exposure rules as --serve: loopback unless an explicit HOST,
    --secret authenticates the upstream attach AND every downstream."""
    from gol_tpu.relay import RelayNode

    up = _addr(args.relay)
    host, port = _addr(args.serve, default_host="127.0.0.1")
    try:
        relay = RelayNode(
            up, host, port,
            secret=args.secret,
            session=args.session,
            batch_turns=(args.batch_turns
                         if args.batch_turns is not None else 1024),
            heartbeat_secs=args.hb_secs,
            evict_secs=args.evict_secs,
            max_peers=args.max_peers,
            high_water=args.high_water,
            drain_secs=args.drain_secs,
            writer_pool_threads=args.writer_pool_threads,
            ws_port=args.ws_port,
            reconnect_window=args.reconnect_secs,
        )
    except ValueError as e:
        raise SystemExit(f"error: {e}") from None
    print(f"relay serving on {relay.address[0]}:{relay.address[1]} "
          f"(upstream {up[0]}:{up[1]})")
    if relay.ws_address is not None:
        print(f"websocket gateway on "
              f"{relay.ws_address[0]}:{relay.ws_address[1]}")
    metrics = _start_metrics(args, health=relay.health)
    from gol_tpu.obs import flight as _flight

    _flight.set_state_provider(relay.health)
    relay.start()
    try:
        while not relay.wait(timeout=1.0):
            pass
    except KeyboardInterrupt:
        relay.shutdown()
    finally:
        if metrics is not None:
            metrics.close()
    return 0


def _control_plane(args) -> int:
    """Fleet controller (gol_tpu.control; docs/CONTROL.md): load the
    declarative spec (a parse error aborts AT STARTUP, exactly the
    --alert-rules discipline), then reconcile forever. The sidecar
    serves the controller's own metrics + /healthz, so the console —
    and another controller — can observe the observer."""
    from gol_tpu.control import Controller, SpecError, load_spec

    try:
        spec = load_spec(args.control)
        ctl = Controller(spec, out_dir=args.out)
    except SpecError as e:
        raise SystemExit(f"error: {e}") from None
    print(f"controller reconciling {args.control} "
          f"(root {spec.root}, {len(spec.engines)} engine(s), "
          f"relays {spec.relay_min}..{spec.relay_max})")
    metrics = _start_metrics(args, health=ctl.health)
    from gol_tpu.obs import flight as _flight

    _flight.set_state_provider(ctl.health)
    ctl.start()
    try:
        while not ctl.wait(timeout=1.0):
            pass
    except KeyboardInterrupt:
        ctl.shutdown()
    finally:
        if metrics is not None:
            metrics.close()
    return 0


def _collector(args, resume: bool) -> int:
    """History-plane collector (gol_tpu.obs.collector + .tsdb;
    docs/OBSERVABILITY.md "History plane"): ingest remote-write
    telemetry from every sidecar into crash-atomic segment logs under
    <out>/tsdb and serve range queries (/query, /history) from its own
    metrics sidecar. Same exposure rules as --serve: loopback unless
    an explicit HOST, --secret gates every remote-write attach.

    --alert-rules here evaluate FLEET-WIDE: the evaluator reads the
    collected series (each key tagged src="SOURCE") instead of the
    collector's own registry, and after --resume latest the `for:`
    clocks are seeded from stored history — a restart cannot reset a
    breach that was already pending."""
    import time as _time

    from gol_tpu.obs import freshness as _freshness
    from gol_tpu.obs.collector import CollectorServer
    from gol_tpu.obs.tsdb import TSDB, eval_expr

    host, port = _addr(args.collector, default_host="127.0.0.1")
    root = os.path.join(args.out, "tsdb")
    db = TSDB(root, resume=resume)
    if resume:
        print(f"resumed {len(db.sources())} source(s) from {root}/")
    server = CollectorServer(host, port, db, secret=args.secret)
    print(f"collector serving on "
          f"{server.address[0]}:{server.address[1]} (store {root}/)")

    def health():
        last = db.last_sample_time()
        return {
            "status": "ok", "mode": "collector",
            "sources": len(db.sources()),
            "last_sample_age_s": (None if last is None
                                  else round(_time.time() - last, 3)),
        }

    def fleet_series():
        # Merged latest values across every source, each key tagged
        # src="..." — `max(family)` in a rule means "worst source".
        merged = {}
        now = _time.time()
        for src in db.sources():
            for key, value in db.latest(src, max_age=60.0,
                                        now=now).items():
                name, brace, rest = key.partition("{")
                if brace:
                    merged[f'{name}{{src="{src}",{rest}'] = value
                else:
                    merged[f'{name}{{src="{src}"}}'] = value
        return merged

    # One try from here down: a SIGINT landing anywhere after the
    # banner (even mid-seeding) must still reach the graceful close
    # (final segment flushed), not escape as an uncaught interrupt.
    metrics = None
    try:
        metrics = _start_metrics(args, health=health, tsdb=db,
                                 series_source=fleet_series)
        if metrics is not None and metrics.alerts is not None \
                and resume:
            ev = metrics.alerts
            now_wall = _time.time()

            def stored_values(rule):
                # Ages relative to now, one point per evaluator
                # interval over the trailing 2x `for:` window.
                window = max(10.0, 2.0 * rule.for_secs)
                step = max(1.0, ev.interval)
                pts = eval_expr(db, rule.agg, rule.family,
                                now_wall - window, now_wall, step)
                return [(now_wall - t, v) for t, v in pts
                        if v is not None]

            seeded = ev.seed_history(stored_values)
            if seeded:
                print(f"seeded {seeded} for: rule(s) pending from "
                      "stored history")
        from gol_tpu.obs import flight as _flight

        _flight.set_state_provider(health)
        server.start()
        while True:
            _time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        if metrics is not None:
            metrics.close()
        server.close()  # closes the TSDB (final segment flushed)
    return 0


def _control(args, params: Params, keypresses: queue.Queue) -> int:
    """Controller attached to a remote engine (ref: README.md:177-183)."""
    from gol_tpu.distributed import Controller

    host, port = _addr(args.connect)
    from gol_tpu.models.rules import GenRule

    # params.rule already holds the parsed rule object (main validated
    # it) — one derivation point for the level-mode decision.
    vis_levels = isinstance(params.rule, GenRule)
    # batch=True: the visualiser applies each turn's flips as one
    # vectorized XOR (events.FlipBatch) instead of per-cell objects;
    # levels follows the rule family (gray-level gens batches, r5).
    ctl = Controller(host, port, want_flips=not args.novis,
                     secret=args.secret, batch=not args.novis,
                     batch_turns=args.batch_turns,
                     levels=vis_levels and not args.novis,
                     observe=args.observe,
                     session=args.session,
                     reconnect=not args.no_reconnect,
                     reconnect_window=args.reconnect_secs)

    def _ctl_health() -> dict:
        return {
            "status": "ok" if not ctl.events.closed else "detached",
            "state": ctl.state,
            "synced": ctl.synced.is_set(),
            "sync_turn": ctl.sync_turn,
            "reconnects": ctl.reconnects,
            "detached": ctl.detached.is_set(),
        }

    metrics = None

    class _WireKeys:
        """queue.Queue-shaped sink that forwards verbs over the wire —
        lets the visualiser loop and the stdin pump share one path."""

        def put(self, key):
            try:
                ctl.send_key(key)
            except (OSError, ConnectionError):
                pass

    wire_keys = _WireKeys()

    def pump():  # local stdin verbs → remote engine
        while True:
            try:
                wire_keys.put(keypresses.get(timeout=0.2))
            except queue.Empty:
                if ctl.detached.is_set() or ctl.events.closed:
                    return  # detached, lost, or run over

    threading.Thread(target=pump, name="gol-ctl-keys", daemon=True).start()
    try:
        # Inside the try: a failed sidecar bind must still detach the
        # controller (ctl.close() in the finally frees the driver slot).
        metrics = _start_metrics(args, health=_ctl_health)
        from gol_tpu.obs import flight as _flight

        _flight.set_state_provider(_ctl_health)
        if args.novis:
            for ev in ctl.events:
                s = str(ev)
                if s:
                    print(f"Completed Turns {ev.completed_turns:<8}{s}")
            if ctl.lost.is_set():
                print("error: connection to the engine lost "
                      "(reconnect budget exhausted)", file=sys.stderr)
                return 1
            if ctl.board is None and not ctl.detached.is_set():
                print("engine run ended before the attach completed",
                      file=sys.stderr)
        else:
            from gol_tpu.visual import run_loop

            # The engine's board size wins over local -w/-h flags: the
            # attach sync carries the authoritative dimensions. Running
            # with unconfirmed local dimensions would blow up on the
            # first out-of-range flip, so a failed sync aborts instead.
            if not (ctl.wait_sync() and ctl.board is not None):
                print("error: no board sync from the engine (attach "
                      "failed or run already over)", file=sys.stderr)
                return 1
            h, w = ctl.board.shape
            params = dataclasses.replace(
                params, image_width=w, image_height=h
            )
            run_loop(params, ctl.events, wire_keys, levels=vis_levels)
            if ctl.lost.is_set():
                # Same contract as the headless path: a permanently
                # lost link is a failure exit, not a silent 0.
                print("error: connection to the engine lost "
                      "(reconnect budget exhausted)", file=sys.stderr)
                return 1
        return 0
    finally:
        ctl.close()
        if metrics is not None:
            metrics.close()


if __name__ == "__main__":
    sys.exit(main())
