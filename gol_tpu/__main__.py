"""`python -m gol_tpu` — process entry (ref: main.go)."""

import sys

from gol_tpu.cli import main

sys.exit(main())
