"""gol_tpu.sessions — the multi-tenant session layer: S boards, one jit.

Every layer below this one (engine, wire, obs, resilience) assumed
exactly one board per process; production traffic is many SMALL boards
(ROADMAP open item 3). This package turns the engine into a service:

- **buckets** — sessions with the same (height, width, rule) stack
  into one `(S, H/32, W)` packed device array stepped by a single
  vmapped/jitted dispatch (`parallel.stepper.make_batch_stepper`), so
  S tenants amortize one dispatch's fixed overhead instead of paying
  it S times;
- **padding / slot reuse** — free slots are zero boards stepped along
  with the tenants; create/destroy inside a warm bucket only touch
  TRACED slot indices, so joins and leaves never recompile (the PR 1
  recompile discipline, pinned by the jit-cache census test);
- **per-session diff streams** — watched buckets ride the PR 4
  variable-length compact encoding vmapped per session; each session's
  decoded flip rows feed the existing wire encodings unchanged;
- **lifecycle verbs** — create / destroy / checkpoint / list, exposed
  over the wire by `distributed.server.SessionServer` (CLI:
  `--serve --sessions`) and driven by `distributed.client.SessionControl`;
  watching peers attach with `Controller(session="id")`;
- **checkpoint/resume** — per-session PGM snapshots under
  `out/sessions/<id>/` with a `session.json` sidecar; `--resume latest`
  restores every session (composing with the PR 3 crash-restart story);
- **bounded observability** — per-session metric labels
  (`gol_tpu_session_turns_total{session=...}`) are EVICTED at destroy
  (`obs.Registry.remove`), so the registry cannot grow without bound
  under churn; lifecycle and dispatch land on the PR 2/PR 5 planes.

Model: docs/SESSIONS.md.
"""

from gol_tpu.sessions.manager import (
    Session,
    SessionError,
    SessionManager,
    Sink,
    valid_session_id,
)
from gol_tpu.sessions.engine import SessionEngine

__all__ = [
    "Session",
    "SessionEngine",
    "SessionError",
    "SessionManager",
    "Sink",
    "valid_session_id",
]
