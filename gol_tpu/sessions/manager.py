"""SessionManager — bucketed multi-tenant board ownership.

Threading contract (the engine-thread discipline of
`engine.distributor`, applied to buckets): when a `SessionEngine` is
running, ITS thread is the only one that touches device arrays —
public verbs from other threads post requests the engine services
between dispatches. Without an engine (tests, the bench), the calling
thread owns the device and verbs execute inline. Bookkeeping dicts are
guarded by one lock either way, so `list_sessions` is safe from any
thread and never touches the device.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import threading
import time
from typing import Callable, Optional

import numpy as np

from gol_tpu import obs
from gol_tpu.models.rules import GenRule, LIFE, Rule, get_rule
from gol_tpu.obs import accounting, device, flight, tracing
from gol_tpu.analysis.concurrency import lockcheck

#: Session ids are path components (checkpoints live under
#: out/sessions/<id>/) and metric label values — one conservative
#: charset serves both, and rejects traversal outright.
SESSION_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")

#: Per-session registry series — the exact set `destroy` evicts
#: (tests pin that the registry shrinks back under churn).
PER_SESSION_SERIES = (
    "gol_tpu_session_turns_total",
    "gol_tpu_session_watchers",
)

# Bounded-cardinality audit: every per-session series is declared to
# the registry's shared eviction helper, so ONE evict_entity call at
# destroy/park removes the whole set (and the churn test can assert
# the registry ends where it started).
obs.track_entity_series("session", *PER_SESSION_SERIES)

#: Board-dimension sanity bound for wire-driven creates: a hostile
#: create must not make the server allocate an arbitrary raster.
MAX_SESSION_CELLS = 4096 * 4096

#: Minimum per-turn changed-words cap once the compact encoding
#: engages (the engine's DIFF_SPARSE_MIN_CAP, same rationale).
COMPACT_MIN_CAP = 64


def valid_session_id(sid) -> bool:
    return isinstance(sid, str) and bool(SESSION_ID_RE.match(sid))


def seeded_board(height: int, width: int, seed: int,
                 density: float = 0.25) -> np.ndarray:
    """The deterministic soup a seeded create starts from — one
    derivation shared by `create`, manifest-driven resume, and the
    chaos harness's unfaulted oracle (`gol_tpu.testing.chaos`), so
    "bit-identical to an unfaulted run" is checkable from the recipe
    alone."""
    rng = np.random.default_rng(int(seed))
    return ((rng.random((height, width)) < float(density))
            .astype(np.uint8) * np.uint8(255))


class SessionError(ValueError):
    """A session verb failed for a caller-visible reason (unknown id,
    duplicate create, invalid geometry/rule). The message is the wire
    `reason` — keep it one short token-ish phrase."""


class _SessionMetrics:
    """Registry handles for the session plane (gol_tpu.obs). Bucket-
    and process-level series are unbounded-lifetime; per-SESSION
    children are created at `create` and evicted at `destroy` (see
    PER_SESSION_SERIES)."""

    def __init__(self):
        self.active = obs.gauge(
            "gol_tpu_sessions_active", "Currently live sessions"
        )
        self.buckets = obs.gauge(
            "gol_tpu_session_buckets", "Shape/rule buckets currently held"
        )
        self.creates = obs.counter(
            "gol_tpu_session_creates_total", "Sessions created"
        )
        self.destroys = obs.counter(
            "gol_tpu_session_destroys_total", "Sessions destroyed"
        )
        self.checkpoints = obs.counter(
            "gol_tpu_session_checkpoints_total",
            "Per-session PGM checkpoints written",
        )
        self.resumes = obs.counter(
            "gol_tpu_session_resumes_total",
            "Sessions restored from per-session checkpoints",
        )
        self.parked = obs.gauge(
            "gol_tpu_sessions_parked",
            "Sessions currently hibernated (checkpointed, device "
            "rows freed; rehydrated bit-exactly on attach)",
        )
        self.hibernates = obs.counter(
            "gol_tpu_session_hibernates_total",
            "Sessions parked to their checkpoint (idle policy or the "
            "park verb)",
        )
        self.rehydrates = obs.counter(
            "gol_tpu_session_rehydrates_total",
            "Parked sessions restored into a bucket slot on attach",
        )
        self.adoptions = obs.counter(
            "gol_tpu_session_adoptions_total",
            "Sessions adopted from ANOTHER manager's checkpoint tree "
            "(control-plane migration: park on A, adopt on B)",
        )
        paths = ("fused", "diffs", "compact")
        self.dispatches = {
            p: obs.counter(
                "gol_tpu_session_dispatches_total",
                "Bucket dispatches by path", {"path": p},
            ) for p in paths
        }
        self.dispatch_seconds = {
            p: obs.histogram(
                "gol_tpu_session_dispatch_seconds",
                "Host-blocking seconds per bucket dispatch", {"path": p},
            ) for p in paths
        }
        self.compact_redos = obs.counter(
            "gol_tpu_session_compact_redos_total",
            "Bucket chunks redone densely after a value-buffer overflow",
        )
        self.bucket_grows = obs.counter(
            "gol_tpu_session_bucket_grows_total",
            "Bucket capacity doublings (each is one recompile)",
        )


_METRICS = _SessionMetrics()


class Sink:
    """Per-session event consumer protocol. All callbacks run on the
    dispatching thread (the SessionEngine's, or the caller's in inline
    mode) — implementations must be non-blocking (the server sink
    enqueues to per-connection writer queues). Exceptions raised by a
    sink detach it."""

    #: Sinks that don't want per-turn flip payloads still get
    #: `on_sync`/`on_turn`/`on_close`.
    want_flips = True

    #: EPHEMERAL sinks (the replay plane's RecorderSink) never count
    #: as watchers for the hibernation policy: a session whose only
    #: sink is ephemeral still idles, still parks (the park closes the
    #: ephemeral sink with reason "parked"), and its `info()` watcher
    #: count stays honest. They DO count for the dispatch path —
    #: recording needs the diff stream.
    ephemeral = False

    #: A POSITIVE value makes this sink chunk-granular: the manager
    #: hands whole dispatched chunks to `on_flip_chunk` instead of the
    #: per-turn on_flips/on_turn loop, and the SessionEngine scales
    #: the bucket's dispatch chunk up to this many turns (the batched
    #: wire, ISSUE 10). 0 = per-turn callbacks (the legacy contract,
    #: preserved).
    batch_turns = 0

    def on_sync(self, sid: str, turn: int, board: np.ndarray) -> None:
        """Full board state at attach (and after any resync)."""

    def on_flips(self, sid: str, turn: int, coords: np.ndarray) -> None:
        """One turn's flipped cells as an (N, 2) int32 x,y array —
        exactly the single-board engine's FlipBatch payload."""

    def on_flip_chunk(self, sid: str, first_turn: int, counts,
                      bitmaps, words) -> None:
        """A whole dispatched chunk for this session in the S-sparse
        layout (events.FlipChunk: per-turn changed-word counts,
        bitmaps, concatenated XOR masks), covering turns
        `first_turn .. first_turn + len(counts) - 1`. Called instead
        of the per-turn loop when `batch_turns` > 0 and the bucket is
        packed; a chunk-granular sink does its own per-turn
        bookkeeping."""

    def on_turn(self, sid: str, turn: int) -> None:
        """A turn committed for this session."""

    def on_close(self, sid: str, reason: str) -> None:
        """The session is gone (destroyed / manager shutdown)."""


class Session:
    """One tenant: a slot in a bucket plus its own turn clock."""

    def __init__(self, sid: str, bucket: "_Bucket", slot: int,
                 start_turn: int, seed: Optional[int] = None,
                 density: float = 0.25):
        self.id = sid
        self.bucket = bucket
        self.slot = slot
        self.start_turn = start_turn
        #: Creation recipe, when the board came from a seeded soup —
        #: recorded in the session manifest so a crash BEFORE the first
        #: checkpoint still resumes deterministically (the manifest
        #: entry alone can rebuild the turn-0 board).
        self.seed = seed
        self.density = density
        self.birth_ticks = bucket.ticks
        self.created_at = time.time()
        #: monotonic instant this session last lost its final sink
        #: (or was created sinkless) — the auto-park policy's idle
        #: clock; None while anything is attached.
        self.idle_since: Optional[float] = time.monotonic()
        # Per-session labeled children — evicted at destroy.
        self.turns_metric = obs.counter(
            "gol_tpu_session_turns_total",
            "Turns committed per live session (evicted at destroy)",
            {"session": sid},
        )
        self.watchers_metric = obs.gauge(
            "gol_tpu_session_watchers",
            "Sinks attached per live session (evicted at destroy)",
            {"session": sid},
        )

    @property
    def turn(self) -> int:
        """Completed turns: sessions in a bucket step in lockstep, so a
        session's clock is its resume offset plus the bucket ticks
        since it joined."""
        return self.start_turn + (self.bucket.ticks - self.birth_ticks)

    def info(self) -> dict:
        b = self.bucket
        return {
            "id": self.id,
            "width": b.width,
            "height": b.height,
            "rule": str(b.rule),
            "turn": self.turn,
            # Ephemeral sinks (recorders) are plumbing, not watchers.
            "watchers": len(_watching(b.sinks.get(self.id, ()))),
            "bucket": b.key,
        }


def _watching(sinks) -> list:
    """The NON-ephemeral sinks of one session — what the idle/park
    policy and the watcher counts mean by "watched"."""
    return [sk for sk in (sinks or ())
            if not getattr(sk, "ephemeral", False)]


class _Bucket:
    """One (height, width, rule) shape class: a BatchStepper, its
    stacked device state, and the slot bookkeeping."""

    def __init__(self, height: int, width: int, rule: Rule,
                 capacity: int, dev=None):
        from gol_tpu.parallel.stepper import make_batch_stepper

        self.height, self.width, self.rule = height, width, rule
        self.key = f"{width}x{height}/{rule}"
        self.device = dev
        # Compiles fired while a bucket is built/warmed are attributed
        # to it on the device plane (the compile watcher's cause).
        with device.cause("bucket-new"):
            self.bs = make_batch_stepper(capacity, height, width, rule,
                                         dev)
            zero = np.zeros((height, width), np.uint8)
            self.stack = self.bs.put_all([zero] * capacity)
        if device.cost_probes_enabled():
            cost = device.publish_cost(
                "bucket.step",
                lambda st: self.bs.step_n(st, 1)[0], self.stack,
            )
            m = accounting.meter()
            if m is not None:
                # Per-bucket FLOPs price: one step of the WHOLE stack
                # — the accounting plane splits it across the bucket's
                # live tenants at dispatch time.
                m.set_price(f"bucket.step:{self.key}", cost)
        #: Free slots, lowest first (pop from the end).
        self.free = list(range(capacity - 1, -1, -1))
        self.sessions: "dict[int, Session]" = {}   # slot -> Session
        self.sinks: "dict[str, list[Sink]]" = {}   # sid -> sinks
        #: Total turns this bucket has stepped since creation — every
        #: occupied slot advances by exactly this clock.
        self.ticks = 0
        #: Per-slot activity weights (changed-word counts) of the last
        #: watched dispatch — the accounting plane's bucket-split rule;
        #: None after a fused dispatch (equal turn-weighted shares).
        self.last_weights: "Optional[dict]" = None
        #: Adaptive per-turn changed-words cap for the compact path
        #: (None = not yet enabled; next watched chunk runs plain
        #: diffs to observe activity). Pow2 with 2x headroom, exactly
        #: the engine's `_adapt_sparse_cap` hysteresis.
        self.compact_cap: Optional[int] = None
        self.last_save_tick = 0

    @property
    def live(self) -> int:
        return len(self.sessions)

    def watched(self) -> bool:
        return any(self.sinks.get(s.id) for s in self.sessions.values())

    def flip_watched(self) -> bool:
        return any(
            sink.want_flips
            for s in self.sessions.values()
            for sink in self.sinks.get(s.id, ())
        )

    def batch_hint(self) -> int:
        """Negotiated batch pacing for this bucket's dispatch chunk —
        the SessionEngine raises a watched bucket's chunk to it, so a
        batching watcher isn't pinned at the 16-turn interactive chunk
        (ISSUE 10's chunk-pinning fix). Sessions in a bucket step in
        LOCKSTEP, so the raise only happens when EVERY attached sink
        is chunk-granular (one per-turn watcher anywhere in the bucket
        keeps the interactive chunk — the tenant paying the latency
        must be one who negotiated it), and the SMALLEST negotiated
        max-k paces the bucket (conservative: nobody's whole-batch
        latency exceeds their own negotiation)."""
        hints = [getattr(sink, "batch_turns", 0)
                 for s in self.sessions.values()
                 for sink in self.sinks.get(s.id, ())]
        if not hints or 0 in hints:
            return 0
        return min(hints)

    def adapt_cap(self, peak_words: int) -> None:
        ceiling = self.bs.total_words // 2
        if (not self.bs.offers("step_n_with_diffs_compact")
                or ceiling < COMPACT_MIN_CAP or 2 * peak_words > ceiling):
            new = None
        else:
            want = (
                max(COMPACT_MIN_CAP, 1 << (2 * peak_words - 1).bit_length())
                if peak_words else COMPACT_MIN_CAP
            )
            new = min(want, 1 << (ceiling.bit_length() - 1))
        if new != self.compact_cap:
            # Each distinct cap is one recompile of the k-turn scan —
            # timeline-worthy, exactly like the engine's sparse cap.
            tracing.event("session.compact_cap", "engine",
                          bucket=self.key, cap=new, peak=peak_words)
        self.compact_cap = new


class SessionManager:
    def __init__(self, *, out_dir: str = "out",
                 default_rule: "Rule | str" = LIFE,
                 bucket_capacity: int = 16,
                 autosave_turns: int = 0,
                 max_sessions: Optional[int] = None,
                 park_idle_secs: Optional[float] = None,
                 device=None):
        if bucket_capacity < 1:
            raise ValueError("bucket_capacity must be >= 1")
        self.out_dir = out_dir
        self.default_rule = (get_rule(default_rule)
                             if isinstance(default_rule, str)
                             else default_rule)
        self.bucket_capacity = bucket_capacity
        self.autosave_turns = max(0, int(autosave_turns))
        #: Admission budget (docs/RESILIENCE.md "Overload &
        #: degradation"): creates beyond this raise
        #: SessionError("max-sessions") — the server turns that into an
        #: over-budget rejection with a retry_after hint. None = no cap.
        #: The budget counts RESIDENT sessions only: parked sessions
        #: hold no device rows, so hibernation turns --max-sessions
        #: from an HBM bound into an admission-rate bound
        #: (docs/SESSIONS.md "Hibernation").
        self.max_sessions = max_sessions
        #: Idle-hibernation policy: sessions with no sink (watcher or
        #: driver) for this many seconds are parked by `park_idle`
        #: (the SessionEngine sweeps it every loop round). 0 parks at
        #: the first idle sweep; None (default) never auto-parks.
        self.park_idle_secs = park_idle_secs
        #: Replay-plane recording state (gol_tpu.replay): when the
        #: serving layer records sessions it sets this (e.g.
        #: {"keyframe_turns": K}) and every session.json sidecar
        #: carries it under "record" — the durable mark that a
        #: session's out/sessions/<id>/replay/ log is live.
        self.record_meta: "Optional[dict]" = None
        #: Recorder factory `(sid, width, height) -> Optional[Sink]`:
        #: when set (SessionServer --record), EVERY `_create` — wire
        #: verb, resume, rehydration — attaches the returned ephemeral
        #: sink INSIDE the create, on the owner thread, before the
        #: session's first dispatch: the recording's first keyframe is
        #: the birth (or revival) board, never a few chunks late.
        self.recorder_factory = None
        #: Hibernated sessions: sid -> manifest-shaped meta (width/
        #: height/rule/seed/density + parked/turn). No device rows,
        #: no bucket slot — just the durable record; `_rehydrate`
        #: turns an entry back into a live Session on attach.
        self._parked: "dict[str, dict]" = {}
        self.device = device
        #: True only inside `resume_all`: restoring creates defer the
        #: manifest rewrite to one commit at the end of the resume.
        self._restoring = False
        #: True only inside `_park_idle`: a parking sweep defers the
        #: manifest rewrite to one commit at the end (same rationale).
        self._deferring_manifest = False
        self._buckets: "dict[tuple, _Bucket]" = {}
        self._by_id: "dict[str, Session]" = {}
        self._lock = lockcheck.make_rlock("SessionManager._lock")
        #: Cross-thread verb requests: (fn, event, box) serviced by the
        #: engine thread between dispatches (see `_exec`).
        self._requests: list = []
        #: The SessionEngine driving this manager, if any (set by the
        #: engine itself); its kick event wakes an idle loop when a
        #: request lands.
        self._engine = None
        self._kick = threading.Event()
        self._closed = False

    # --- public verbs (any thread) ---

    def create(self, sid: str, *, width: int, height: int,
               rule: "Rule | str | None" = None,
               board: Optional[np.ndarray] = None,
               seed: Optional[int] = None, density: float = 0.25,
               start_turn: int = 0) -> dict:
        """Create a session; returns its info dict. `board` wins over
        `seed` (a deterministic random soup); neither means an empty
        board. Raises SessionError on invalid ids/geometry/rules or a
        duplicate id."""
        if not valid_session_id(sid):
            raise SessionError("bad-session-id")
        if (not isinstance(width, int) or not isinstance(height, int)
                or width <= 0 or height <= 0
                or width * height > MAX_SESSION_CELLS):
            raise SessionError("bad-dimensions")
        try:
            rule_obj = (self.default_rule if rule is None
                        else get_rule(rule) if isinstance(rule, str)
                        else rule)
        except ValueError:
            raise SessionError("bad-rule") from None
        if isinstance(rule_obj, GenRule) or 0 in rule_obj.birth:
            # Two-state only; B0 padding slots would seethe (see
            # BatchStepper's docstring).
            raise SessionError("unsupported-rule")
        if board is None and seed is not None:
            board = seeded_board(height, width, int(seed), float(density))
        if board is not None:
            board = np.asarray(board, np.uint8)
            if board.shape != (height, width):
                raise SessionError("bad-board")
        return self._exec(lambda: self._create(
            sid, width, height, rule_obj, board, int(start_turn),
            seed=None if seed is None else int(seed),
            density=float(density),
        ))

    def destroy(self, sid: str) -> None:
        self._exec(lambda: self._destroy(sid, "destroyed"))

    def park(self, sid: str) -> dict:
        """Hibernate a session (docs/SESSIONS.md "Hibernation"):
        checkpoint it (crash-atomic PGM + sidecar), record it parked
        in the manifest, and free its bucket slot (a traced clear —
        zero recompiles in a warm bucket). Raises
        SessionError("watched") while any sink is attached,
        ("parked") when already hibernated. The next attach
        rehydrates it bit-exactly."""
        return self._exec(lambda: self._park(sid))

    def adopt(self, sid: str, source_dir: "str | os.PathLike") -> dict:
        """Adopt a session hibernated under ANOTHER manager's out tree
        (control-plane migration, PR 18: park on engine A, adopt on
        engine B, flip the serving endpoint). Reads the source tree's
        `session.json` sidecar + latest snapshot — the same bit-exact
        state a local rehydrate would load — creates the session
        resident HERE at the snapshot turn, and immediately
        re-checkpoints into THIS manager's own tree so the adopted
        session is durable locally (B's resume never depends on A's
        disk again).

        The source tree is read-only: the parked record on A stays
        A's to destroy (the controller's two-phase migration record
        sequences that). Raises SessionError("exists") for a duplicate
        id, ("unknown-session") when the source has no such session or
        it is tombstoned there, ("unrecoverable") for a torn source
        tree."""
        if not valid_session_id(sid):
            raise SessionError("bad-session-id")
        return self._exec(
            lambda: self._adopt(sid, os.fspath(source_dir)))

    def park_idle(self) -> int:
        """Park every session idle (no sink) past `park_idle_secs` —
        the SessionEngine sweeps this between dispatch rounds (the
        _exec routing keeps the device work on the owner thread for
        any other caller). Returns the number parked; 0 when the
        policy is off."""
        if self.park_idle_secs is None or self._closed:
            return 0
        return self._exec(self._park_idle)

    def _park_idle(self) -> int:
        now = time.monotonic()
        due = [
            s.id for s in list(self._by_id.values())
            if not _watching(s.bucket.sinks.get(s.id))
            and s.idle_since is not None
            and now - s.idle_since >= self.park_idle_secs
        ]
        # One manifest commit for the whole sweep, not one per parked
        # session — a burst of N idle sessions would otherwise rewrite
        # the N-entry manifest N times under the manager lock (O(N²)
        # serialization stalling every verb). The crash window stays
        # bounded-conservative: a session parked in memory but not yet
        # recorded merely resumes LIVE from its just-written snapshot.
        n = 0
        self._deferring_manifest = True
        try:
            for sid in due:
                try:
                    self._park(sid)
                    n += 1
                except (SessionError, OSError):
                    continue
        finally:
            self._deferring_manifest = False
        if n:
            with contextlib.suppress(OSError):
                self._write_manifest()
        return n

    def is_parked(self, sid: str) -> bool:
        return sid in self._parked

    def parked_meta(self, sid: str) -> Optional[dict]:
        """A parked session's manifest-shaped record (width/height/
        rule/seed/density/turn), or None — the full recipe the
        server's idempotent create-retry compare needs (the public
        listing drops seed/density on purpose)."""
        meta = self._parked.get(sid)
        return dict(meta) if meta is not None else None

    def known(self, sid: str) -> bool:
        """Live OR parked — what an attach may name (lock-free dict
        membership, the peek_turn discipline)."""
        return sid in self._by_id or sid in self._parked

    def peek_geometry(self, sid: str) -> "Optional[tuple[int, int]]":
        """(width, height) of a live or parked session, lock-free;
        None for unknown ids."""
        s = self._by_id.get(sid)
        if s is not None:
            return s.bucket.width, s.bucket.height
        meta = self._parked.get(sid)
        if meta is not None:
            return meta.get("width"), meta.get("height")
        return None

    def checkpoint(self, sid: str) -> dict:
        """Write out/sessions/<sid>/<W>x<H>x<T>.pgm (crash-atomic) plus
        the session.json sidecar; returns {"path", "turn"}."""
        return self._exec(lambda: self._checkpoint(sid))

    def attach(self, sid: str, sink: Sink) -> dict:
        """Register a sink: it receives `on_sync` with the current
        board at the next dispatch boundary, then per-turn callbacks.
        Returns the session info."""
        return self._exec(lambda: self._attach(sid, sink))

    def detach(self, sid: str, sink: Sink) -> None:
        self._exec(lambda: self._detach(sid, sink))

    def fetch_board(self, sid: str) -> np.ndarray:
        """Current (H, W) {0,255} board of a session."""
        return self._exec(lambda: self._fetch_board(sid))

    def list_sessions(self) -> list:
        with self._lock:
            live = [s.info() for s in
                    sorted(self._by_id.values(), key=lambda s: s.id)]
            parked = [
                {"id": sid, "width": meta.get("width"),
                 "height": meta.get("height"),
                 "rule": meta.get("rule"),
                 "turn": int(meta.get("turn", 0)),
                 "watchers": 0, "parked": True}
                for sid, meta in sorted(self._parked.items())
            ]
        return sorted(live + parked, key=lambda i: i["id"])

    def get(self, sid: str) -> Optional[Session]:
        with self._lock:
            return self._by_id.get(sid)

    def peek_turn(self, sid: str) -> int:
        """Lock-free turn hint for liveness paths (the server's
        heartbeat beacons): plain GIL-atomic dict/attribute reads,
        never the manager lock — that lock is held across whole bucket
        dispatches, and a beacon that waits on a cold compile defeats
        its own purpose. May be one dispatch stale; 0 for unknown ids.
        Parked sessions answer their hibernated turn."""
        s = self._by_id.get(sid)
        if s is not None:
            return s.turn
        meta = self._parked.get(sid)
        return int(meta.get("turn", 0)) if meta is not None else 0

    def resume_all(self) -> int:
        """Restore the crash-consistent session set under out/sessions/
        (PR 3's `--resume latest`, per session; docs/SESSIONS.md
        "Crash-consistent resume"). Manifest-first: when
        manifest.json is readable it names EXACTLY the live set as of
        the last completed create/destroy — each listed session resumes
        from its latest snapshot, or, never having checkpointed, is
        rebuilt from its manifest recipe (seeded soup at turn 0).
        Tombstoned sessions are never resurrected in either mode (the
        tombstone lands BEFORE the manifest rewrite, closing the
        SIGKILL-mid-destroy window). A missing/torn manifest falls back
        to the legacy directory scan. Unreadable entries are skipped —
        resume discovery runs on freshly crashed trees. Returns the
        number restored."""
        from gol_tpu.checkpoint import (
            is_tombstoned,
            latest_any_snapshot,
            read_session_manifest,
            session_checkpoint_dir,
            snapshot_turn,
        )
        from gol_tpu.io.pgm import read_pgm

        root = session_checkpoint_dir(self.out_dir)
        manifest = read_session_manifest(self.out_dir)
        if manifest is None:
            try:
                candidates = {
                    sid: None for sid in sorted(os.listdir(root))
                }
            except OSError:
                return 0
        else:
            candidates = {sid: manifest[sid] for sid in sorted(manifest)}
        restored = 0
        # Restoring creates must NOT rewrite the manifest one by one:
        # a crash mid-resume would commit a manifest naming only the
        # sessions restored so far, silently shrinking the
        # authoritative live set — exactly the torn half-set resume
        # exists to prevent. The pre-crash manifest stays authoritative
        # until the whole set is back; ONE rewrite at the end commits
        # it (and repairs a torn manifest after a directory scan).
        from gol_tpu.checkpoint import manifest_parked

        self._restoring = True
        try:
            for sid, meta in candidates.items():
                if (not valid_session_id(sid) or sid in self._by_id
                        or sid in self._parked
                        or is_tombstoned(self.out_dir, sid)):
                    continue
                if manifest_parked(meta):
                    # Hibernated at the crash/restart: restore the
                    # RECORD, not a slot — the fleet stays mostly
                    # asleep across restarts, and the next attach
                    # rehydrates from the snapshot exactly as it
                    # would have pre-restart.
                    self._parked[sid] = dict(meta)
                    restored += 1
                    continue
                found = latest_any_snapshot(os.path.join(root, sid))
                board = turn = None
                if found is not None:
                    path, w, h = found
                    with contextlib.suppress(OSError, ValueError):
                        board = read_pgm(path)
                        turn = snapshot_turn(path)
                rule = (meta or {}).get("rule")
                if rule is None:
                    with contextlib.suppress(OSError, ValueError,
                                             KeyError, TypeError):
                        side = json.loads(open(
                            os.path.join(root, sid, "session.json")
                        ).read())
                        rule = side.get("rule")
                # The creation recipe rides along even on the snapshot
                # path: a resumed session must keep answering a
                # rid-retried identical-recipe create with ok (the
                # state-based idempotency compares seed/density), and
                # the next manifest rewrite must not lose the recipe.
                seed = (meta or {}).get("seed")
                density = (meta or {}).get("density")
                if board is None:
                    # Created, never checkpointed, killed: the manifest
                    # recipe rebuilds the turn-0 board bit-exactly. A
                    # manifest entry with neither snapshot nor seed
                    # cannot be reconstructed and is skipped
                    # (board-injected sessions accept bounded loss
                    # until first checkpoint).
                    if meta is None or seed is None:
                        continue
                    w, h = meta.get("width"), meta.get("height")
                    turn = 0
                try:
                    self.create(
                        sid, width=w, height=h, rule=rule,
                        board=board, seed=seed,
                        density=0.25 if density is None else density,
                        start_turn=int(turn))
                    restored += 1
                except (SessionError, OSError, ValueError, TypeError):
                    continue
        finally:
            self._restoring = False
        if restored:
            with self._lock:
                with contextlib.suppress(OSError):
                    self._write_manifest()
            _METRICS.parked.set(len(self._parked))
            flight.note("sessions.resume", count=restored)
        return restored

    def close(self) -> None:
        """Close every sink and drop all sessions (process teardown)."""

        def _do():
            self._closed = True
            for sid in [s.id for s in self._by_id.values()]:
                self._destroy(sid, "shutdown")

        with contextlib.suppress(TimeoutError):
            self._exec(_do)

    def health(self) -> dict:
        with self._lock:
            return {
                "status": "ok",
                "sessions": len(self._by_id),
                "parked": len(self._parked),
                "buckets": len(self._buckets),
                "ticks": {b.key: b.ticks for b in self._buckets.values()},
            }

    # --- request plumbing ---

    def _exec(self, fn: Callable, timeout: float = 60.0):
        eng = self._engine
        if eng is None or not eng.running() or eng.is_engine_thread():
            with self._lock:
                return fn()
        ev = threading.Event()
        box: dict = {}
        with self._lock:
            self._requests.append((fn, ev, box))
        self._kick.set()
        if not ev.wait(timeout):
            raise TimeoutError("session engine did not service the verb")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _service_requests(self) -> None:
        """Owner thread: run all pending verbs."""
        with self._lock:
            reqs, self._requests = self._requests, []
        for fn, ev, box in reqs:
            try:
                with self._lock:
                    box["result"] = fn()
            except BaseException as e:  # delivered to the caller
                box["error"] = e
            finally:
                ev.set()

    # --- verb implementations (owner thread, lock held via _exec) ---

    def _bucket_for(self, height: int, width: int, rule: Rule,
                    min_free: int = 1) -> _Bucket:
        key = (height, width, str(rule))
        b = self._buckets.get(key)
        if b is None:
            b = _Bucket(height, width, rule, self.bucket_capacity,
                        self.device)
            self._buckets[key] = b
            _METRICS.buckets.set(len(self._buckets))
            tracing.event("session.bucket", "lifecycle", bucket=b.key,
                          capacity=b.bs.capacity)
        while len(b.free) < min_free:
            self._grow(b)
        return b

    def _grow(self, b: _Bucket) -> None:
        """Double a full bucket's capacity: a new BatchStepper (one
        recompile — the documented cost of outgrowing a bucket; slot
        churn within capacity stays compile-free)."""
        from gol_tpu.parallel.stepper import make_batch_stepper

        old_cap = b.bs.capacity
        new_cap = old_cap * 2
        with device.cause("bucket-grow"):
            boards = [b.bs.fetch_one(b.stack, i) for i in range(old_cap)]
            boards += [np.zeros((b.height, b.width), np.uint8)] * old_cap
            b.bs = make_batch_stepper(new_cap, b.height, b.width, b.rule,
                                      b.device)
            b.stack = b.bs.put_all(boards)
        b.free = list(range(new_cap - 1, old_cap - 1, -1)) + b.free
        _METRICS.bucket_grows.inc()
        tracing.event("session.bucket_grow", "lifecycle", bucket=b.key,
                      capacity=new_cap)
        flight.note("session.bucket_grow", bucket=b.key, capacity=new_cap)

    def _create(self, sid: str, width: int, height: int, rule: Rule,
                board: Optional[np.ndarray], start_turn: int,
                seed: Optional[int] = None,
                density: float = 0.25) -> dict:
        if sid in self._by_id or sid in self._parked:
            # A parked session still owns its id (it is one attach
            # away from being live again) — a create over it is a
            # duplicate, exactly as over a resident one.
            raise SessionError("exists")
        if (self.max_sessions is not None
                and len(self._by_id) >= self.max_sessions):
            # Admission budget: the caller (SessionServer) rides a
            # retry_after hint on this reason so a storm backs off
            # instead of hammering a full house.
            raise SessionError("max-sessions")
        b = self._bucket_for(height, width, rule)
        slot = b.free.pop()
        if board is not None:
            b.stack = b.bs.set_one(b.stack, slot, board)
        else:
            b.stack = b.bs.clear_one(b.stack, slot)
        s = Session(sid, b, slot, start_turn, seed=seed, density=density)
        b.sessions[slot] = s
        self._by_id[sid] = s
        # The manifest rewrite is the create's durability commit: a
        # kill before this line leaves no trace to resume (correct —
        # the verb never acked), a kill after it resumes the session
        # from its manifest recipe even with zero checkpoints written.
        # During resume_all the pre-crash manifest stays authoritative
        # instead (one rewrite at the end of the resume).
        if not self._restoring:
            self._write_manifest()
        # A re-created id takes over a DESTROYED predecessor's
        # directory: the dead incarnation's snapshots and tombstone
        # must not survive into the new one (a later `--resume latest`
        # would skip the live session as destroyed, or restore the dead
        # one's board). Strictly AFTER the manifest commit, with the
        # tombstone removed last: every kill window resumes either
        # nothing (tombstone still present) or the new recipe — never
        # the destroyed incarnation. Gated on the tombstone so resuming
        # a live session never wipes its own checkpoint history.
        self._clear_session_remnants(sid)
        _METRICS.creates.inc()
        _METRICS.active.set(len(self._by_id))
        # Device rows changed hands: a (rate-limited) census keeps the
        # HBM watermark honest even for fleets that park before their
        # first dispatch (the churn smoke's flatness gauge).
        device.observe_memory()
        tracing.event("session.create", "lifecycle", session=sid,
                      bucket=b.key, slot=slot, turn=start_turn)
        flight.note("session.create", session=sid, bucket=b.key)
        if self.recorder_factory is not None:
            # Tape from birth: the recorder's attach-time keyframe is
            # THIS board at THIS turn (after remnant clearing, so a
            # re-created id's log starts clean). A recorder that fails
            # to arm never fails the create — the session is the
            # product, the tape is best-effort.
            with contextlib.suppress(Exception):
                sink = self.recorder_factory(sid, b.width, b.height)
                if sink is not None:
                    self._attach(sid, sink)
        return s.info()

    def _clear_session_remnants(self, sid: str) -> None:
        from gol_tpu.checkpoint import (
            is_tombstoned,
            session_checkpoint_dir,
            tombstone_path,
        )

        if not is_tombstoned(self.out_dir, sid):
            return
        d = os.path.join(session_checkpoint_dir(self.out_dir), sid)
        try:
            names = os.listdir(d)
        except OSError:
            return
        for name in names:
            if name.endswith(".pgm") or name == "session.json":
                with contextlib.suppress(OSError):
                    os.unlink(os.path.join(d, name))
        # The dead incarnation's RECORDING must not survive either: a
        # replay server pointed at this tree would serve the destroyed
        # board's history under the new session's id.
        from gol_tpu.replay.log import replay_dir, scan_segments

        for _, seg in scan_segments(replay_dir(d)):
            with contextlib.suppress(OSError):
                os.unlink(seg)
        # Tombstone last: a kill mid-clear must leave the predecessor
        # destroyed (tombstone intact), never half-resurrected.
        with contextlib.suppress(OSError):
            os.unlink(tombstone_path(self.out_dir, sid))

    def _write_manifest(self) -> None:
        """Crash-atomic rewrite of out/sessions/manifest.json — the
        authoritative live-session set for `--resume latest`
        (docs/SESSIONS.md "Crash-consistent resume"). Called under the
        manager lock at every create/destroy, so the file always
        records a verb-boundary state, never a torn half-set."""
        from gol_tpu.checkpoint import session_manifest_path

        path = session_manifest_path(self.out_dir)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        sessions = {}
        for s in sorted(self._by_id.values(), key=lambda s: s.id):
            b = s.bucket
            meta = {"width": b.width, "height": b.height,
                    "rule": str(b.rule)}
            if s.seed is not None:
                meta["seed"] = s.seed
                meta["density"] = s.density
            sessions[s.id] = meta
        # Parked sessions are part of the authoritative set: they must
        # survive a restart AS parked (no slot claimed at resume) and
        # still rehydrate on attach (docs/SESSIONS.md "Hibernation").
        for sid, meta in sorted(self._parked.items()):
            sessions[sid] = dict(meta)
        obs.atomic_write_text(path, json.dumps({"sessions": sessions}))

    def _require(self, sid: str) -> Session:
        s = self._by_id.get(sid)
        if s is None:
            # A parked session is NOT unknown — verbs that need a
            # resident board (checkpoint, fetch) answer "parked" so
            # the caller knows an attach would revive it.
            raise SessionError(
                "parked" if sid in self._parked else "unknown-session"
            )
        return s

    def _destroy(self, sid: str, reason: str) -> None:
        if sid not in self._by_id and sid in self._parked:
            # Destroying a hibernated session: no slot to free — drop
            # the record with the same tombstone-first durability
            # (every kill window leaves it destroyed, never
            # resurrected). A shutdown-close leaves parked sessions
            # parked: they must resume.
            if reason == "shutdown":
                return
            del self._parked[sid]
            self._write_tombstone(sid, reason)
            self._write_manifest()
            _METRICS.destroys.inc()
            _METRICS.parked.set(len(self._parked))
            tracing.event("session.destroy", "lifecycle", session=sid,
                          reason=reason, parked=True)
            flight.note("session.destroy", session=sid, reason=reason)
            return
        s = self._require(sid)
        b = s.bucket
        for sink in b.sinks.pop(sid, []):
            with contextlib.suppress(Exception):
                sink.on_close(sid, reason)
        # Tombstone FIRST, manifest second: every kill window between
        # the two leaves the session destroyed on resume (the manifest
        # may still list it; the tombstone overrules). A shutdown-close
        # is not a destroy — those sessions must resume.
        if reason != "shutdown":
            self._write_tombstone(sid, reason)
        b.stack = b.bs.clear_one(b.stack, s.slot)
        del b.sessions[s.slot]
        b.free.append(s.slot)
        del self._by_id[sid]
        if reason != "shutdown":
            self._write_manifest()
        # Bounded-cardinality contract: the per-session children leave
        # the registry WITH the session (pinned by test_sessions),
        # and so does its live usage view (history stays in the ledger).
        obs.evict_entity("session", sid)
        m = accounting.meter()
        if m is not None:
            m.forget(sid)
        _METRICS.destroys.inc()
        _METRICS.active.set(len(self._by_id))
        tracing.event("session.destroy", "lifecycle", session=sid,
                      reason=reason)
        flight.note("session.destroy", session=sid, reason=reason)

    def _write_tombstone(self, sid: str, reason: str) -> None:
        from gol_tpu.checkpoint import tombstone_path

        path = tombstone_path(self.out_dir, sid)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # Existence IS the record (a truncated tombstone still
        # counts); the payload is forensics for operators.
        obs.atomic_write_text(
            path, json.dumps({"id": sid, "reason": reason,
                              "ts": time.time()}),
        )

    def _fetch_board(self, sid: str) -> np.ndarray:
        s = self._require(sid)
        return s.bucket.bs.fetch_one(s.bucket.stack, s.slot)

    def _checkpoint(self, sid: str) -> dict:
        from gol_tpu.checkpoint import session_checkpoint_dir
        from gol_tpu.io.pgm import write_pgm

        s = self._require(sid)
        b = s.bucket
        d = os.path.join(session_checkpoint_dir(self.out_dir), sid)
        os.makedirs(d, exist_ok=True)
        turn = s.turn
        path = os.path.join(d, f"{b.width}x{b.height}x{turn}.pgm")
        write_pgm(path, self._fetch_board(sid))
        side = {"id": sid, "width": b.width, "height": b.height,
                "rule": str(b.rule), "turn": turn}
        if self.record_meta is not None:
            side["record"] = dict(self.record_meta)
        obs.atomic_write_text(
            os.path.join(d, "session.json"), json.dumps(side),
        )
        _METRICS.checkpoints.inc()
        tracing.event("session.checkpoint", "lifecycle", session=sid,
                      turn=turn)
        return {"path": path, "turn": turn}

    def _park(self, sid: str) -> dict:
        s = self._by_id.get(sid)
        if s is None:
            raise SessionError(
                "parked" if sid in self._parked else "unknown-session"
            )
        b = s.bucket
        if _watching(b.sinks.get(sid)):
            raise SessionError("watched")
        # Ephemeral sinks (recorders) don't block hibernation — they
        # close with the park (their last segment is already durable;
        # the next attach re-arms a recorder off the rehydrated board).
        for sink in list(b.sinks.get(sid, ())):
            with contextlib.suppress(Exception):
                sink.on_close(sid, "parked")
        b.sinks.pop(sid, None)
        # The checkpoint IS the hibernated state: crash-atomic PGM +
        # sidecar at the current turn, so a kill anywhere past this
        # line rehydrates exactly what was parked.
        saved = self._checkpoint(sid)
        meta = {"width": b.width, "height": b.height,
                "rule": str(b.rule), "parked": True,
                "turn": int(saved["turn"])}
        if s.seed is not None:
            meta["seed"] = s.seed
            meta["density"] = s.density
        # Free the device rows: a traced slot clear (zero recompiles
        # in a warm bucket — the create/destroy discipline).
        b.stack = b.bs.clear_one(b.stack, s.slot)
        del b.sessions[s.slot]
        b.free.append(s.slot)
        del self._by_id[sid]
        self._parked[sid] = meta
        # Manifest after the parked record exists in memory: the
        # rewrite commits the parked flag durably (a kill between the
        # checkpoint and this rewrite resumes the session LIVE from
        # its snapshot — bounded conservatism, never loss). The idle
        # sweep defers it to ONE commit per sweep (see _park_idle).
        if not self._deferring_manifest:
            self._write_manifest()
        obs.evict_entity("session", sid)
        m = accounting.meter()
        if m is not None:
            m.forget(sid)
        _METRICS.hibernates.inc()
        _METRICS.parked.set(len(self._parked))
        _METRICS.active.set(len(self._by_id))
        device.observe_memory()
        tracing.event("session.park", "lifecycle", session=sid,
                      turn=meta["turn"])
        flight.note("session.park", session=sid, turn=meta["turn"])
        return {"id": sid, "turn": meta["turn"], "path": saved["path"]}

    def _rehydrate(self, sid: str) -> Session:
        """Parked -> live: read the hibernated snapshot (manifest
        recipe as the torn-disk fallback) and re-create the session in
        its bucket at the recorded turn — bit-exact (PGM snapshots are
        complete state), traced slot writes only (zero recompiles in a
        warm bucket). Raises SessionError("max-sessions") when the
        RESIDENT budget is full — rehydration is an admission, and the
        caller's retry hint applies."""
        from gol_tpu.checkpoint import (
            latest_any_snapshot,
            session_checkpoint_dir,
            snapshot_turn,
        )
        from gol_tpu.io.pgm import read_pgm

        meta = self._parked[sid]
        # A parked record may have been resumed from a torn/hostile
        # manifest: every field access must surface as a SessionError
        # (the server's attach path answers those; anything else would
        # kill its accept machinery).
        try:
            w, h = int(meta["width"]), int(meta["height"])
            rule = get_rule(meta.get("rule") or str(self.default_rule))
            seed = meta.get("seed")
            density = float(meta.get("density", 0.25))
            turn = int(meta.get("turn", 0))
        except (KeyError, TypeError, ValueError):
            raise SessionError("unrecoverable") from None
        d = os.path.join(session_checkpoint_dir(self.out_dir), sid)
        board = None
        found = latest_any_snapshot(d)
        if found is not None:
            path, _w, _h = found
            with contextlib.suppress(OSError, ValueError):
                board = read_pgm(path)
                turn = snapshot_turn(path)
        if board is None and seed is not None:
            # Torn snapshot tree: the recipe still rebuilds turn 0
            # deterministically (bounded loss, never resurrection of
            # garbage).
            board = seeded_board(w, h, int(seed), density)
            turn = 0
        if board is None or board.shape != (h, w):
            # (a snapshot of a different geometry than the manifest
            # claims is a torn tree, not a crash-worthy surprise)
            raise SessionError("unrecoverable")
        del self._parked[sid]
        try:
            self._create(sid, w, h, rule, board, turn,
                         seed=seed, density=density)
        except BaseException:
            self._parked[sid] = meta  # stay parked on any failure
            raise
        _METRICS.rehydrates.inc()
        _METRICS.parked.set(len(self._parked))
        tracing.event("session.rehydrate", "lifecycle", session=sid,
                      turn=turn)
        flight.note("session.rehydrate", session=sid, turn=turn)
        return self._by_id[sid]

    def _adopt(self, sid: str, source_dir: str) -> dict:
        """Owner-thread half of `adopt`: load the FOREIGN tree's
        sidecar + snapshot (read-only), create resident, re-checkpoint
        locally. Mirrors `_rehydrate`'s torn-tree discipline — every
        malformed field is a SessionError, never a crash."""
        from gol_tpu.checkpoint import (
            is_tombstoned,
            latest_any_snapshot,
            session_checkpoint_dir,
            snapshot_turn,
        )
        from gol_tpu.io.pgm import read_pgm

        if sid in self._by_id or sid in self._parked:
            raise SessionError("exists")
        if is_tombstoned(source_dir, sid):
            # Destroyed at the source: adopting it would resurrect a
            # session some verb already acked as gone.
            raise SessionError("unknown-session")
        d = os.path.join(session_checkpoint_dir(source_dir), sid)
        try:
            with open(os.path.join(d, "session.json")) as f:
                side = json.load(f)
        except (OSError, ValueError):
            raise SessionError("unknown-session") from None
        try:
            w, h = int(side["width"]), int(side["height"])
            rule = get_rule(side.get("rule") or str(self.default_rule))
            turn = int(side.get("turn", 0))
        except (KeyError, TypeError, ValueError):
            raise SessionError("unrecoverable") from None
        if w <= 0 or h <= 0 or w * h > MAX_SESSION_CELLS:
            raise SessionError("unrecoverable")
        board = None
        found = latest_any_snapshot(d)
        if found is not None:
            path, _w, _h = found
            with contextlib.suppress(OSError, ValueError):
                board = read_pgm(path)
                turn = snapshot_turn(path)
        if board is None or board.shape != (h, w):
            # No complete snapshot (or one of a different geometry
            # than the sidecar claims): nothing bit-exact to adopt.
            raise SessionError("unrecoverable")
        info = self._create(sid, w, h, rule, board, turn)
        # Durability lands HERE before the verb acks: the adopted
        # session must resume from THIS tree even if the source
        # engine's disk disappears the moment the migration commits.
        self._checkpoint(sid)
        _METRICS.adoptions.inc()
        tracing.event("session.adopt", "lifecycle", session=sid,
                      turn=turn, source=source_dir)
        flight.note("session.adopt", session=sid, turn=turn)
        return info

    def _attach(self, sid: str, sink: Sink) -> dict:
        s = self._by_id.get(sid)
        if s is None and sid in self._parked:
            # Attach is the rehydration trigger: a parked session
            # comes back resident, bit-exact, before the sync below.
            s = self._rehydrate(sid)
        elif s is None:
            raise SessionError("unknown-session")
        b = s.bucket
        board = self._fetch_board(sid)
        sink.on_sync(sid, s.turn, board)
        b.sinks.setdefault(sid, []).append(sink)
        if not getattr(sink, "ephemeral", False):
            # Only real watchers stop the idle clock: a recorder-only
            # session still auto-parks (docs/SESSIONS.md).
            s.idle_since = None
        s.watchers_metric.set(len(_watching(b.sinks[sid])))
        tracing.event("session.attach", "lifecycle", session=sid)
        return s.info()

    def _detach(self, sid: str, sink: Sink) -> None:
        s = self._by_id.get(sid)
        if s is None:
            return
        sinks = s.bucket.sinks.get(sid, [])
        with contextlib.suppress(ValueError):
            sinks.remove(sink)
        if not sinks:
            s.bucket.sinks.pop(sid, None)
        if not _watching(sinks) and s.idle_since is None:
            # The idle clock starts when the LAST watcher leaves — the
            # auto-park policy's trigger (ephemeral sinks don't hold
            # the session awake).
            s.idle_since = time.monotonic()
        s.watchers_metric.set(len(_watching(sinks)))
        tracing.event("session.detach", "lifecycle", session=sid)

    def resync(self, sid: str, sink: Sink, prepare=None) -> None:
        """Serve `sink` a FRESH BoardSync on the engine thread,
        between dispatches (the replay plane's live-rejoin: a scrubbed
        peer returns to the present contiguously — `prepare` runs
        first, atomically with the sync, e.g. clearing the scrub
        flag). Raises SessionError for unknown/parked ids."""

        def _do():
            s = self._require(sid)
            if prepare is not None:
                prepare()
            sink.on_sync(sid, s.turn, self._fetch_board(sid))

        self._exec(_do)

    # --- the bucketed dispatch loop (owner thread) ---

    def pump(self, turns: int, chunk: Optional[int] = None) -> None:
        """Inline stepping (no engine thread): advance every occupied
        bucket by exactly `turns` turns in up-to-`chunk`-sized
        dispatches (dispatches may come back cadence-capped — see
        `_dispatch_bucket`)."""

        def _do():
            for b in list(self._buckets.values()):
                if not b.live:
                    continue
                left = turns
                while left > 0:
                    left -= self._dispatch_bucket(
                        b, min(left, chunk or turns)
                    )

        self._exec(_do)

    def _dispatch_bucket(self, b: _Bucket, k: int) -> int:
        """One dispatch of up to `k` turns for one bucket; returns the
        turns actually stepped (the autosave cadence may cap k so a
        kill loses at most one cadence interval — the engine's
        bounded-loss contract, per bucket)."""
        if self.autosave_turns > 0:
            k = max(1, min(
                k, b.last_save_tick + self.autosave_turns - b.ticks
            ))
        t0 = time.perf_counter()
        wall0 = time.time()
        if b.flip_watched():
            with device.cause("bucket-dispatch"):
                path = self._dispatch_diffs(b, k)
        else:
            with device.cause("bucket-dispatch"):
                b.stack, _counts = b.bs.step_n(b.stack, k)
            device.observe_split(enqueue_s=time.perf_counter() - t0)
            path = "fused"
            self._commit(b, k)
            if b.watched():
                # Sinks that declined flip payloads still get their
                # per-turn on_turn callbacks (the singleton engine
                # emits TurnComplete to every synced peer regardless
                # of want_flips — same contract here).
                self._emit(b, k, {})
        dt = time.perf_counter() - t0
        _METRICS.dispatches[path].inc()
        _METRICS.dispatch_seconds[path].observe(dt)
        m = accounting.meter()
        if m is not None and b.sessions:
            # Attribute the ONE shared vmapped dispatch to its tenants:
            # activity-weighted when the diff headers produced per-slot
            # changed-word counts, equal turn-weighted on the fused
            # path. Conservation-checked inside (shares sum to dt).
            items = list(b.sessions.items())
            w = b.last_weights if path != "fused" else None
            m.charge_bucket(
                [s.id for _, s in items],
                None if w is None else [w.get(slot, 0.0)
                                        for slot, _ in items],
                seconds=dt,
                flops=m.price_flops(f"bucket.step:{b.key}") * k,
                turns=k, what=b.key,
            )
        tracing.add_span(
            "session.dispatch", "engine", wall0, dt,
            {"bucket": b.key, "path": path, "turns": k,
             "sessions": b.live},
        )
        if (self.autosave_turns > 0
                and b.ticks - b.last_save_tick >= self.autosave_turns):
            b.last_save_tick = b.ticks
            for s in list(b.sessions.values()):
                with contextlib.suppress(OSError):
                    self._checkpoint(s.id)
        return k

    def _dispatch_diffs(self, b: _Bucket, k: int) -> str:
        """One watched dispatch: compact when the adaptive cap is live
        (overflow -> dense redo, never trust a dropped-write buffer),
        plain per-session diff stacks otherwise. Demuxes the decoded
        per-turn rows to each watched session's sinks — the identical
        flip stream the single-board engine would have produced for
        that board (pinned by bit-equality tests)."""
        from gol_tpu.parallel.stepper import (
            compact_decode_rows,
            compact_value_bucket,
        )

        path = "diffs"
        rows_by_slot = None
        if b.compact_cap is not None:
            path = "compact"
            total_cap = k * b.compact_cap
            enq0 = time.perf_counter()
            stack, headers, values, counts = (
                b.bs.step_n_with_diffs_compact(b.stack, k, total_cap)
            )
            enq_s = time.perf_counter() - enq0
            sync0 = time.perf_counter()
            hdr = np.ascontiguousarray(np.asarray(headers)).view(np.uint32)
            totals = hdr[:, :, 0].sum(axis=1)
            if totals.size and int(totals.max()) > total_cap:
                # Activity burst past the shared buffer in at least one
                # session: redo the whole bucket chunk densely from the
                # pre-dispatch stack (bit-identical result).
                b.compact_cap = None
                _METRICS.compact_redos.inc()
                tracing.event("session.compact_redo", "engine",
                              bucket=b.key, total_cap=total_cap)
                flight.note("session.compact_redo", bucket=b.key)
                return self._dispatch_diffs(b, k)
            # One bounded-shape slice fetches every session's used
            # prefix (bucketed, so the per-chunk slice compiles a
            # bounded set of shapes — compact_value_bucket).
            n = min(int(values.shape[1]),
                    compact_value_bucket(int(totals.max()) if totals.size
                                         else 0))
            vals = np.ascontiguousarray(
                np.asarray(values[:, :n])
            ).view(np.uint32)
            sync_s = time.perf_counter() - sync0
            b.stack = stack
            self._commit(b, k)
            host0 = time.perf_counter()
            rows_by_slot = {}
            chunks_by_slot = {}
            weights = {}
            peak = 0
            for slot, s in b.sessions.items():
                hs = hdr[slot]
                peak = max(peak, int(hs[:, 0].max()) if hs.size else 0)
                # Activity weight = this tenant's changed words across
                # the chunk (the accounting plane's split rule).
                weights[slot] = float(hs[:, 0].sum()) if hs.size else 0.0
                sinks = b.sinks.get(s.id)
                if not sinks:
                    continue
                if any(getattr(sk, "batch_turns", 0) for sk in sinks):
                    # Chunk-granular sinks ride the device layout
                    # directly — counts/bitmaps are the header, the
                    # values slice is the used prefix; no dense
                    # scatter for these sessions.
                    counts_s = hs[:, 0].astype(np.int64)
                    chunks_by_slot[slot] = (
                        counts_s, hs[:, 1:],
                        vals[slot][:int(counts_s.sum())],
                    )
                if any(not getattr(sk, "batch_turns", 0)
                       for sk in sinks):
                    rows_by_slot[slot] = list(compact_decode_rows(
                        hs, vals[slot], b.bs.total_words
                    ))
            b.last_weights = weights
            b.adapt_cap(peak)
        else:
            enq0 = time.perf_counter()
            stack, diffs, counts = b.bs.step_n_with_diffs(b.stack, k)
            enq_s = time.perf_counter() - enq0
            sync0 = time.perf_counter()
            host = np.asarray(diffs)
            sync_s = time.perf_counter() - sync0
            b.stack = stack
            self._commit(b, k)
            host0 = time.perf_counter()
            rows_by_slot = {}
            chunks_by_slot = {}
            weights = {}
            peak = 0
            for slot, s in b.sessions.items():
                d = host[slot]
                weights[slot] = float(np.count_nonzero(d))
                if b.bs.packed:
                    peak = max(
                        peak,
                        max((int(np.count_nonzero(d[t]))
                             for t in range(k)), default=0),
                    )
                sinks = b.sinks.get(s.id)
                if not sinks:
                    continue
                if b.bs.packed and any(
                        getattr(sk, "batch_turns", 0) for sk in sinks):
                    from gol_tpu.parallel.stepper import (
                        sparse_chunk_from_dense,
                    )

                    chunks_by_slot[slot] = sparse_chunk_from_dense(
                        np.asarray(d).reshape(k, -1)
                    )
                if any(not getattr(sk, "batch_turns", 0)
                       for sk in sinks) or not b.bs.packed:
                    rows_by_slot[slot] = [
                        d[t].reshape(-1) for t in range(k)
                    ]
            b.last_weights = weights
            if b.bs.packed:
                b.adapt_cap(peak)
        self._emit(b, k, rows_by_slot, chunks_by_slot)
        # Device-vs-host split of this bucket dispatch (same boundaries
        # as the singleton engine: enqueue / materialise / decode+emit).
        device.observe_split(enq_s, sync_s,
                             time.perf_counter() - host0)
        return path

    def _commit(self, b: _Bucket, k: int) -> None:
        b.ticks += k
        for s in b.sessions.values():
            s.turns_metric.inc(k)
        flight.note("sessions.commit", bucket=b.key, ticks=b.ticks)
        # BatchStepper dispatches bypass instrument_stepper, so the
        # memory census (rate-limited inside) rides the commit.
        device.observe_memory()

    def _emit(self, b: _Bucket, k: int, rows_by_slot: dict,
              chunks_by_slot: "Optional[dict]" = None) -> None:
        """Fan one dispatched chunk out to the attached sinks, per
        session: chunk-granular sinks get the whole chunk in ONE
        on_flip_chunk call, per-turn sinks keep the legacy
        flips-then-turn loop in turn order."""
        from gol_tpu.ops.bitlife import unpack_np
        from gol_tpu.utils.cell import xy_from_mask

        hw = b.height // 32 if b.bs.packed else None
        for slot, s in list(b.sessions.items()):
            sinks = b.sinks.get(s.id)
            if not sinks:
                continue
            chunk = (chunks_by_slot or {}).get(slot)
            if chunk is not None:
                dead = []
                for sink in [sk for sk in sinks
                             if getattr(sk, "batch_turns", 0)]:
                    try:
                        sink.on_flip_chunk(s.id, s.turn - k + 1, *chunk)
                    except Exception:
                        dead.append(sink)
                for sink in dead:
                    self._detach(s.id, sink)
                sinks = [sk for sk in (b.sinks.get(s.id) or ())
                         if not getattr(sk, "batch_turns", 0)]
                if not sinks:
                    continue
            rows = rows_by_slot.get(slot)
            base = s.turn - k
            for t in range(k):
                turn = base + t + 1
                coords = None
                if rows is not None:
                    row = rows[t]
                    if b.bs.packed:
                        mask = unpack_np(
                            np.asarray(row).reshape(hw, b.width), b.height
                        ) != 0
                    else:
                        mask = np.asarray(row).reshape(b.height, b.width)
                    coords = xy_from_mask(mask)
                dead = []
                for sink in sinks:
                    try:
                        if coords is not None and sink.want_flips \
                                and len(coords):
                            sink.on_flips(s.id, turn, coords)
                        sink.on_turn(s.id, turn)
                    except Exception:
                        dead.append(sink)
                for sink in dead:
                    self._detach(s.id, sink)
                # Re-read survivors, still EXCLUDING chunk-granular
                # sinks when this session's chunk was already handed
                # out above (they must not also get the per-turn loop).
                sinks = [sk for sk in (b.sinks.get(s.id) or ())
                         if chunk is None
                         or not getattr(sk, "batch_turns", 0)]
                if not sinks:
                    break
