"""SessionEngine — the bucketed dispatch loop.

One thread owns every bucket's device state (the single-device-owner
discipline of `engine.distributor.Engine`, applied across tenants):
it services cross-thread session verbs between dispatches, then steps
each occupied bucket — one vmapped/jitted dispatch per bucket per
round — and demuxes the per-session diff rows to attached sinks.

Chunking: watched buckets run short chunks (verb latency and flip
delivery stay interactive); unwatched buckets run long fused chunks
(dispatch overhead amortizes — the whole point of the layer).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from gol_tpu.obs import flight
from gol_tpu.sessions.manager import SessionManager


class SessionEngine:
    #: Turns per dispatch while any session in the bucket has a
    #: watcher (short: events are decoded + fanned out per chunk).
    WATCHED_CHUNK = 16
    #: Turns per dispatch for unwatched buckets.
    IDLE_CHUNK = 256

    def __init__(self, manager: SessionManager, *,
                 watched_chunk: Optional[int] = None,
                 idle_chunk: Optional[int] = None):
        self.manager = manager
        self.watched_chunk = watched_chunk or self.WATCHED_CHUNK
        self.idle_chunk = idle_chunk or self.IDLE_CHUNK
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None

    def start(self) -> "SessionEngine":
        # Non-daemon for the same reason Engine is: interpreter
        # shutdown mid-dispatch tears down XLA under a live frame. The
        # interpreter-exit stop hook in engine.distributor bounds the
        # wait (register_live_engine duck-types stop()/join()).
        from gol_tpu.engine.distributor import register_live_engine

        self.manager._engine = self
        self._thread = threading.Thread(target=self._run,
                                        name="gol-sessions")
        register_live_engine(self)
        self._thread.start()
        return self

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._stop.is_set()

    def is_engine_thread(self) -> bool:
        """True on the dispatching thread itself — verbs issued from
        sink callbacks (e.g. a server dropping a dead peer mid-demux)
        must run inline, not enqueue-and-wait on themselves."""
        return threading.current_thread() is self._thread

    def stop(self) -> None:
        self._stop.set()
        self.manager._kick.set()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def health(self) -> dict:
        info = self.manager.health()
        if self.error is not None:
            info["status"] = "error"
            info["error"] = repr(self.error)
        return info

    # --- engine thread ---

    def _run(self) -> None:
        m = self.manager
        try:
            while not self._stop.is_set():
                m._service_requests()
                if self._stop.is_set():
                    break
                # Hibernation sweep (docs/SESSIONS.md): sessions idle
                # past the park policy checkpoint and free their slot
                # — the fleet is mostly asleep, and the engine only
                # rounds over buckets with resident tenants.
                m.park_idle()
                did = False
                with m._lock:
                    buckets = [b for b in m._buckets.values() if b.live]
                for b in buckets:
                    # Any watcher — flips or turn-events only — gets
                    # the short interactive chunk; the dispatch path
                    # (diffs vs fused) is flip_watched's call. When
                    # EVERY watcher on the bucket is a BATCHING one
                    # (negotiated hello "batch"), the chunk rises to
                    # the smallest negotiated max-k: they consume
                    # whole k-turn frames, so pinning them at the
                    # interactive size would cap throughput at
                    # 16-turn hops (ISSUE 10's chunk-pinning fix) —
                    # while one per-turn watcher anywhere in the
                    # lockstep bucket keeps the interactive pacing
                    # (see _Bucket.batch_hint).
                    k = (max(self.watched_chunk, b.batch_hint())
                         if b.watched() else self.idle_chunk)
                    with m._lock:
                        if b.live:
                            m._dispatch_bucket(b, k)
                            did = True
                    # Verbs posted mid-round land between bucket
                    # dispatches, not after the whole sweep.
                    m._service_requests()
                    if self._stop.is_set():
                        break
                if not did:
                    m._kick.wait(0.05)
                    m._kick.clear()
        except BaseException as e:
            self.error = e
            flight.note("sessions.fatal", error=repr(e))
            import contextlib

            with contextlib.suppress(Exception):
                flight.dump("sessions-exception")
            raise
        finally:
            # Release any requester still waiting: their verbs run
            # inline once running() is False.
            self._stop.set()
            m._service_requests()
            time.sleep(0)  # let waiters observe the events
