// Native pixel-board visualiser core — the C++ analog of the reference's
// SDL window wrapper (ref: sdl/window.go:22-104: NewWindow, FlipPixel,
// SetPixel, CountPixels, ClearPixels, RenderFrame, PollEvent).
//
// Two modes behind one C API:
//  - headless: an in-memory ARGB8888 framebuffer (the shadow board the
//    reference's -noVis tests keep by hand, ref: sdl_test.go:18-90);
//  - windowed: the same framebuffer presented through libSDL2, loaded at
//    RUNTIME with dlopen so this file builds on machines without SDL2
//    headers. Only the frozen SDL2 ABI surface we need is declared below.
//
// Exposed as a flat C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O2 -fPIC -shared -o libgolvis.so board.cpp -ldl

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <dlfcn.h>

// ---- minimal SDL2 ABI (stable since 2.0) ----------------------------------
// Types are opaque pointers; the event is a 56-byte union we index at the
// documented, ABI-frozen offsets (SDL_KeyboardEvent: u32 type; keysym.sym
// is an i32 at byte 20 = type+timestamp+windowID+state/repeat/padding+scancode).
namespace sdl {
constexpr uint32_t INIT_VIDEO = 0x20;
constexpr uint32_t WINDOWPOS_UNDEFINED = 0x1FFF0000u;
constexpr uint32_t PIXELFORMAT_ARGB8888 = 0x16362004u;
constexpr int TEXTUREACCESS_STREAMING = 1;
constexpr uint32_t EV_QUIT = 0x100;
constexpr uint32_t EV_KEYDOWN = 0x300;

using InitFn = int (*)(uint32_t);
using QuitFn = void (*)();
using CreateWindowFn = void* (*)(const char*, int, int, int, int, uint32_t);
using DestroyWindowFn = void (*)(void*);
using CreateRendererFn = void* (*)(void*, int, uint32_t);
using DestroyRendererFn = void (*)(void*);
using CreateTextureFn = void* (*)(void*, uint32_t, int, int, int);
using DestroyTextureFn = void (*)(void*);
using UpdateTextureFn = int (*)(void*, const void*, const void*, int);
using RenderClearFn = int (*)(void*);
using RenderCopyFn = int (*)(void*, void*, const void*, const void*);
using RenderPresentFn = void (*)(void*);
using PollEventFn = int (*)(void*);

struct Api {
  void* lib = nullptr;
  InitFn Init;
  QuitFn Quit;
  CreateWindowFn CreateWindow;
  DestroyWindowFn DestroyWindow;
  CreateRendererFn CreateRenderer;
  DestroyRendererFn DestroyRenderer;
  CreateTextureFn CreateTexture;
  DestroyTextureFn DestroyTexture;
  UpdateTextureFn UpdateTexture;
  RenderClearFn RenderClear;
  RenderCopyFn RenderCopy;
  RenderPresentFn RenderPresent;
  PollEventFn PollEvent;

  bool load() {
    if (lib) return true;
    lib = dlopen("libSDL2-2.0.so.0", RTLD_NOW | RTLD_GLOBAL);
    if (!lib) lib = dlopen("libSDL2.so", RTLD_NOW | RTLD_GLOBAL);
    if (!lib) return false;
    auto sym = [&](const char* n) { return dlsym(lib, n); };
    Init = (InitFn)sym("SDL_Init");
    Quit = (QuitFn)sym("SDL_Quit");
    CreateWindow = (CreateWindowFn)sym("SDL_CreateWindow");
    DestroyWindow = (DestroyWindowFn)sym("SDL_DestroyWindow");
    CreateRenderer = (CreateRendererFn)sym("SDL_CreateRenderer");
    DestroyRenderer = (DestroyRendererFn)sym("SDL_DestroyRenderer");
    CreateTexture = (CreateTextureFn)sym("SDL_CreateTexture");
    DestroyTexture = (DestroyTextureFn)sym("SDL_DestroyTexture");
    UpdateTexture = (UpdateTextureFn)sym("SDL_UpdateTexture");
    RenderClear = (RenderClearFn)sym("SDL_RenderClear");
    RenderCopy = (RenderCopyFn)sym("SDL_RenderCopy");
    RenderPresent = (RenderPresentFn)sym("SDL_RenderPresent");
    PollEvent = (PollEventFn)sym("SDL_PollEvent");
    return Init && CreateWindow && CreateRenderer && CreateTexture &&
           UpdateTexture && RenderClear && RenderCopy && RenderPresent &&
           PollEvent;
  }
};

Api& api() {
  static Api a;
  return a;
}
}  // namespace sdl

// ---- board ----------------------------------------------------------------

struct Board {
  int w = 0, h = 0;
  uint32_t* pixels = nullptr;  // ARGB8888, row-major (ref: sdl/window.go:38-43)
  // SDL objects (null when headless).
  void* win = nullptr;
  void* ren = nullptr;
  void* tex = nullptr;
  bool sdl_inited = false;
};

extern "C" {

// want_window: 0 = headless shadow board, 1 = try SDL (falls back to
// headless when libSDL2 is absent or window creation fails).
Board* golvis_create(int w, int h, int want_window) {
  if (w <= 0 || h <= 0) return nullptr;
  Board* b = new Board;
  b->w = w;
  b->h = h;
  b->pixels = (uint32_t*)std::calloc((size_t)w * h, 4);
  if (!b->pixels) {
    delete b;
    return nullptr;
  }
  if (want_window && sdl::api().load()) {
    auto& s = sdl::api();
    if (s.Init(sdl::INIT_VIDEO) == 0) {
      b->sdl_inited = true;
      b->win = s.CreateWindow("gol_tpu", (int)sdl::WINDOWPOS_UNDEFINED,
                              (int)sdl::WINDOWPOS_UNDEFINED, w, h, 0);
      if (b->win) {
        b->ren = s.CreateRenderer(b->win, -1, 0);
        if (b->ren)
          b->tex = s.CreateTexture(b->ren, sdl::PIXELFORMAT_ARGB8888,
                                   sdl::TEXTUREACCESS_STREAMING, w, h);
      }
    }
  }
  return b;
}

int golvis_has_window(Board* b) { return b && b->tex ? 1 : 0; }

// XOR the pixel — flipping twice restores it (ref: sdl/window.go:78-88).
// Out-of-range coordinates are a hard error in the reference (panic);
// here they return -1 so the caller can raise.
int golvis_flip_pixel(Board* b, int x, int y) {
  if (!b || x < 0 || x >= b->w || y < 0 || y >= b->h) return -1;
  b->pixels[(size_t)y * b->w + x] ^= 0xFFFFFFFFu;
  return 0;
}

int golvis_set_pixel(Board* b, int x, int y, int on) {
  if (!b || x < 0 || x >= b->w || y < 0 || y >= b->h) return -1;
  b->pixels[(size_t)y * b->w + x] = on ? 0xFFFFFFFFu : 0u;
  return 0;
}

int golvis_get_pixel(Board* b, int x, int y) {
  if (!b || x < 0 || x >= b->w || y < 0 || y >= b->h) return -1;
  return b->pixels[(size_t)y * b->w + x] != 0;
}

// Count of lit pixels (ref: sdl/window.go:90-99) — the shadow-board
// alive count the protocol tests assert on (ref: sdl_test.go:66-74).
long golvis_count_pixels(Board* b) {
  if (!b) return -1;
  long n = 0;
  const size_t total = (size_t)b->w * b->h;
  for (size_t i = 0; i < total; ++i) n += b->pixels[i] != 0;
  return n;
}

void golvis_clear(Board* b) {
  if (b) std::memset(b->pixels, 0, (size_t)b->w * b->h * 4);
}

// Bulk load a {0,nonzero} byte mask — one call instead of W*H set_pixel
// round-trips through ctypes (no reference analog; the Go loop flips
// pixel-by-pixel because its events arrive cell-by-cell).
void golvis_load_mask(Board* b, const uint8_t* mask) {
  if (!b || !mask) return;
  const size_t total = (size_t)b->w * b->h;
  for (size_t i = 0; i < total; ++i) b->pixels[i] = mask[i] ? 0xFFFFFFFFu : 0u;
}

// XOR a {0,nonzero} byte mask of flipped cells into the board — the bulk
// analog of a burst of FlipPixel calls.
void golvis_flip_mask(Board* b, const uint8_t* mask) {
  if (!b || !mask) return;
  const size_t total = (size_t)b->w * b->h;
  for (size_t i = 0; i < total; ++i)
    if (mask[i]) b->pixels[i] ^= 0xFFFFFFFFu;
}

// ---- gray-level mode (multi-state Generations rules, r5) ------------------
// A level v in 0..255 renders as the gray ARGB pixel FF·vvvvvv (0 stays
// fully dead/black). The two-state ops above remain valid on the same
// framebuffer: 255 encodes to 0xFFFFFFFF, exactly the lit pixel.

static inline uint32_t encode_level(uint8_t v) {
  return v ? (0xFF000000u | ((uint32_t)v * 0x010101u)) : 0u;
}

// Bulk load a full gray byte grid — golvis_load_mask generalized to levels.
void golvis_load_levels(Board* b, const uint8_t* levels) {
  if (!b || !levels) return;
  const size_t total = (size_t)b->w * b->h;
  for (size_t i = 0; i < total; ++i) b->pixels[i] = encode_level(levels[i]);
}

// Set every masked cell to its grid level — the bulk form of a level
// FlipBatch (levels SET cells; two-state batches XOR them).
void golvis_update_levels(Board* b, const uint8_t* mask,
                          const uint8_t* levels) {
  if (!b || !mask || !levels) return;
  const size_t total = (size_t)b->w * b->h;
  for (size_t i = 0; i < total; ++i)
    if (mask[i]) b->pixels[i] = encode_level(levels[i]);
}

int golvis_set_level(Board* b, int x, int y, int level) {
  if (!b || x < 0 || x >= b->w || y < 0 || y >= b->h) return -1;
  if (level < 0 || level > 255) return -1;
  b->pixels[(size_t)y * b->w + x] = encode_level((uint8_t)level);
  return 0;
}

int golvis_get_level(Board* b, int x, int y) {
  if (!b || x < 0 || x >= b->w || y < 0 || y >= b->h) return -1;
  return (int)(b->pixels[(size_t)y * b->w + x] & 0xFFu);
}

// Two-state toggle on a gray board: nonzero -> dead, dead -> alive
// (full level). The raw ARGB XOR of golvis_flip_mask would turn grays
// into invalid encodings; this keeps every pixel a valid level.
void golvis_toggle_mask(Board* b, const uint8_t* mask) {
  if (!b || !mask) return;
  const size_t total = (size_t)b->w * b->h;
  for (size_t i = 0; i < total; ++i)
    if (mask[i]) b->pixels[i] = b->pixels[i] ? 0u : encode_level(255);
}

// Count of cells at exactly this gray level (255 = the alive count the
// protocol tests assert; dying levels give the per-level histogram).
long golvis_count_level(Board* b, int level) {
  if (!b || level < 0 || level > 255) return -1;
  const uint32_t want = encode_level((uint8_t)level);
  long n = 0;
  const size_t total = (size_t)b->w * b->h;
  for (size_t i = 0; i < total; ++i) n += b->pixels[i] == want;
  return n;
}

// Present the framebuffer (ref: sdl/window.go:56-64). No-op headless.
void golvis_render(Board* b) {
  if (!b || !b->tex) return;
  auto& s = sdl::api();
  s.UpdateTexture(b->tex, nullptr, b->pixels, b->w * 4);
  s.RenderClear(b->ren);
  s.RenderCopy(b->ren, b->tex, nullptr, nullptr);
  s.RenderPresent(b->ren);
}

// Next pending keydown as its SDL keycode (ASCII for letter keys), 0 if
// none, -1 on window close (ref: sdl/loop.go:14-28 maps keysyms to runes).
int golvis_poll_key(Board* b) {
  if (!b || !b->tex) return 0;
  auto& s = sdl::api();
  alignas(8) uint8_t ev[64];
  while (s.PollEvent(ev)) {
    uint32_t type;
    std::memcpy(&type, ev, 4);
    if (type == sdl::EV_QUIT) return -1;
    if (type == sdl::EV_KEYDOWN) {
      int32_t sym;
      std::memcpy(&sym, ev + 20, 4);  // keysym.sym, ABI-frozen offset
      return sym;
    }
  }
  return 0;
}

void golvis_destroy(Board* b) {
  if (!b) return;
  auto& s = sdl::api();
  if (b->tex) s.DestroyTexture(b->tex);
  if (b->ren) s.DestroyRenderer(b->ren);
  if (b->win) s.DestroyWindow(b->win);
  if (b->sdl_inited) s.Quit();
  std::free(b->pixels);
  delete b;
}

}  // extern "C"
