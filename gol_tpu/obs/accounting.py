"""Accounting plane — per-principal resource attribution + usage ledger.

Every resource the serving plane spends is attributed to a
**principal**: a session id (the bucketed multi-tenant path), a peer
token (`peer:<token>`, wire-level clients that never attached a
session), or the anonymous singleton engine (`legacy`). Metered
resources, one vocabulary everywhere (live series, ledger, `/usage`,
the console's TOP view):

- ``dispatch_seconds``  host-blocking device dispatch time;
- ``flops``             modeled FLOPs — the device plane's `cost_of`
                        program price × dispatched turns (0 until a
                        price is published, i.e. without
                        `--cost-probes`);
- ``host_seconds``      host encode/decode time at the span
                        boundaries (wire.encode_*);
- ``wire_bytes``        frame payload bytes enqueued to the peer, at
                        every tier (EngineServer, SessionServer,
                        relay, WS — all sends pass one `_Conn` hook);
- ``queue_frame_seconds`` writer-queue occupancy — queued frames
                        integrated over the heartbeat sweep interval;
- ``turns``             turns advanced on behalf of the principal.

The hard case is the bucketed session path: S tenants share ONE
vmapped dispatch, so `charge_bucket` splits each measured bucket total
by a declared rule — activity-weighted (per-slot changed-word counts
from the diff/compact headers) when the dispatch produced them, equal
turn-weighted shares otherwise — with a **conservation invariant**:
the shares sum EXACTLY to the measured total (the last share absorbs
the float remainder; any residual increments
`gol_tpu_invariant_violations_total{checker="accounting-conservation"}`
and raises under `GOL_TPU_CHECK_INVARIANTS=1`).

Usage is exposed three ways:

- live bounded-cardinality series: one `TopKGauge` per resource
  (`gol_tpu_usage_<resource>{principal=...}`), children evicted at
  session destroy / peer detach through the registry's shared
  `evict_entity` helper;
- a crash-atomic append-only **ledger**: JSONL delta records in
  size-rolled segments (`usage-<pid>-*.jsonl`), append+flush per
  batch from a dedicated thread (never under a serving lock), torn
  tails tolerated by the reader — `python -m gol_tpu.obs.report
  usage DIR` aggregates segments across processes/incarnations;
- the `/usage` endpoint on every metrics sidecar (`payload()`), which
  `obs.console` joins into the fleet TOP-by-cost view.

Soft budgets (`--session-budget-flops/-bytes`) mark principals
over-budget in the payload and on the `gol_tpu_usage_over_budget`
gauge (alert-rule food) — deliberately NOT enforced: this plane is
the substrate placement/rate-limit decisions will act on, not the
enforcer.

`GOL_TPU_ACCOUNTING=0` disables everything: `meter()` answers None,
so every call site's one-branch guard skips metering entirely — zero
wrappers, zero ledger I/O. Stdlib only, like the registry below it;
all metering is host-side at dispatch/event granularity, never inside
a trace (enforced by the obs-in-jit check).
"""

from __future__ import annotations

import contextlib
import importlib
import itertools
import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence

# The obs package re-binds the NAME `gol_tpu.obs.registry` to its
# same-named convenience FUNCTION (the tracing.py idiom), so the
# submodule must be imported by path.
_reg = importlib.import_module("gol_tpu.obs.registry")

__all__ = [
    "LEGACY",
    "LedgerWriter",
    "Meter",
    "RESOURCES",
    "charge",
    "check_conservation",
    "configure",
    "enabled",
    "ledger_close",
    "meter",
    "payload",
    "read_ledger",
    "set_enabled",
    "split_shares",
]

#: The metered resource vocabulary — ledger records, live series and
#: `/usage` payloads all key by exactly these.
RESOURCES = ("dispatch_seconds", "flops", "host_seconds", "wire_bytes",
             "queue_frame_seconds", "turns")

#: The anonymous singleton-engine tenant (pre-session serving tier).
LEGACY = "legacy"

#: Live-series cardinality bound (the TopKGauge cap) — the top
#: spenders an operator wants named; the ledger keeps everyone.
USAGE_TOPK = 16

_HELP = {
    "dispatch_seconds": "Attributed device dispatch seconds per principal",
    "flops": "Attributed modeled FLOPs (cost_of price x turns) per "
             "principal",
    "host_seconds": "Attributed host encode/decode seconds per principal",
    "wire_bytes": "Attributed wire payload bytes per principal",
    "queue_frame_seconds": "Writer-queue occupancy (queued frames x "
                           "sweep seconds) per principal",
    "turns": "Turns advanced per principal",
}

#: Conservation tolerance: shares are forced to sum exactly, so any
#: residual past float noise is a split-rule bug, not rounding.
_CONSERVE_TOL = 1e-6


def split_shares(total: float, weights: Optional[Sequence[float]],
                 n: Optional[int] = None) -> List[float]:
    """Split `total` into shares proportional to `weights` (equal
    shares when weights are absent or sum to zero). The LAST share
    absorbs the floating-point remainder, so the shares sum to `total`
    exactly — the conservation invariant holds by construction."""
    if weights is None:
        if not n:
            return []
        weights = [1.0] * n
    k = len(weights)
    if k == 0:
        return []
    total = float(total)
    wsum = float(sum(weights))
    if wsum <= 0.0:
        shares = [total / k] * k
    else:
        shares = [total * (float(w) / wsum) for w in weights]
    shares[-1] = total - sum(shares[:-1])
    return shares


def check_conservation(total: float, shares: Iterable[float],
                       what: str = "bucket") -> bool:
    """Assert attributed shares sum to the measured total. Returns
    True when conserved; a breach increments the invariant-violation
    counter (and raises under GOL_TPU_CHECK_INVARIANTS=1) — the PR 1
    checker idiom, applied to money instead of stream order."""
    err = abs(float(total) - float(sum(shares)))
    if err <= _CONSERVE_TOL * max(1.0, abs(float(total))):
        return True
    _VIOLATIONS.inc()
    msg = (f"accounting split of {what} lost {err:g} of {total:g} — "
           "attributed shares must sum to the measured bucket total")
    from gol_tpu.obs import flight

    flight.note("invariant.violation", checker="accounting-conservation",
                msg=msg)
    if os.environ.get("GOL_TPU_CHECK_INVARIANTS", "") == "1":
        from gol_tpu.analysis.invariants import InvariantViolation

        raise InvariantViolation(msg)
    return False


_VIOLATIONS = _reg.counter(
    "gol_tpu_invariant_violations_total",
    "Distributed-protocol invariant violations observed at runtime",
    {"checker": "accounting-conservation"},
)


# --- the ledger ----------------------------------------------------------

#: Disambiguates same-millisecond writers within one process (tests,
#: meter reconfiguration) — part of each writer's segment stamp.
_WRITER_SEQ = itertools.count()


class LedgerWriter:
    """Crash-safe append-only usage ledger: JSONL delta records in
    size-rolled segments under `directory`, written by a DEDICATED
    daemon thread (ledger I/O never runs under a serving lock — the
    drain callable swaps the pending map under the meter's own lock
    and the file write happens lock-free). Discipline matches the
    replay recorder: append + flush per batch, rollover past
    `max_segment_bytes` onto a fresh segment, torn tails are the
    reader's job (`read_ledger` skips them, never raises)."""

    def __init__(self, directory: str, drain,
                 max_segment_bytes: int = 4 << 20,
                 flush_secs: float = 1.0):
        self.directory = directory
        self.max_segment_bytes = int(max_segment_bytes)
        self.flush_secs = float(flush_secs)
        self._drain = drain
        self._seq = 0
        self._rec_seq = 0
        self._file = None
        self._stop = threading.Event()
        os.makedirs(directory, exist_ok=True)
        #: Segment names carry pid + a per-boot stamp (wall millis +
        #: a per-process writer counter): one writer per file, so
        #: concurrent processes, incarnations after a SIGKILL restart,
        #: and same-millisecond writers in one process never
        #: interleave within a segment.
        self._stamp = (f"{os.getpid()}-"
                       f"{int(time.time() * 1000) & 0xFFFFFF:06x}"
                       f"{next(_WRITER_SEQ) & 0xFF:02x}")
        self._thread = threading.Thread(
            target=self._run, name="gol-usage-ledger", daemon=True,
        )
        self._thread.start()

    def _segment_path(self) -> str:
        return os.path.join(
            self.directory, f"usage-{self._stamp}-{self._seq:04d}.jsonl"
        )

    def _rollover_if_needed(self) -> None:
        if self._file is None:
            self._file = open(self._segment_path(), "ab")
            return
        try:
            if self._file.tell() < self.max_segment_bytes:
                return
            self._file.close()
        except (OSError, ValueError):
            pass
        self._seq += 1
        self._file = open(self._segment_path(), "ab")

    def flush_once(self) -> int:
        """Drain pending deltas and append one record per principal;
        returns records written. Failures are swallowed — the ledger
        is best-effort forensics, never a serving-path hazard."""
        pending = self._drain()
        if not pending:
            return 0
        n = 0
        try:
            self._rollover_if_needed()
            for principal in sorted(pending):
                res = {k: v for k, v in pending[principal].items() if v}
                if not res:
                    continue
                self._rec_seq += 1
                line = json.dumps({
                    "ts": round(time.time(), 3),
                    "pid": os.getpid(),
                    "seq": self._rec_seq,
                    "principal": principal,
                    "res": res,
                }, sort_keys=True)
                self._file.write(line.encode() + b"\n")
                n += 1
            self._file.flush()
        except (OSError, ValueError):
            pass
        return n

    def _run(self) -> None:
        while not self._stop.wait(self.flush_secs):
            self.flush_once()
        self.flush_once()  # final drain on close

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        if self._file is not None:
            with contextlib.suppress(OSError, ValueError):
                self._file.close()
            self._file = None


def read_ledger(directory: str) -> Dict[str, Dict[str, float]]:
    """Aggregate every `usage-*.jsonl` segment under `directory` into
    per-principal resource totals. Tolerant by contract: unreadable
    files, torn tails, half-written or interleaved garbage lines are
    skipped — the totals are the sum of every INTACT record, and this
    never raises on hostile trees (fuzzed by tests/test_accounting.py).
    """
    totals: Dict[str, Dict[str, float]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return totals
    for name in names:
        if not (name.startswith("usage-") and name.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(directory, name), "rb") as f:
                blob = f.read()
        except OSError:
            continue
        for raw in blob.split(b"\n"):
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
                principal = rec["principal"]
                res = rec["res"]
                items = [(str(k), float(v)) for k, v in res.items()]
            except (ValueError, KeyError, TypeError, AttributeError):
                continue  # torn tail / corrupt record: skip, never raise
            if not isinstance(principal, str):
                continue
            t = totals.setdefault(principal, {})
            for k, v in items:
                t[k] = t.get(k, 0.0) + v
    return totals


# --- the meter -----------------------------------------------------------


class Meter:
    """Process-global usage meter: `charge` accumulates per-principal
    resource totals (live TopK series + pending ledger deltas) under
    one lock; `charge_bucket` splits a shared vmapped dispatch across
    its tenants conservation-checked. All methods are cheap, host-side
    and callable from any thread; the ledger thread is the only file
    writer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._totals: Dict[str, Dict[str, float]] = {}
        self._pending: Dict[str, Dict[str, float]] = {}
        self._grand: Dict[str, float] = dict.fromkeys(RESOURCES, 0.0)
        self._prices: Dict[str, Dict[str, float]] = {}
        self._budgets: Dict[str, Optional[float]] = {
            "flops": None, "bytes": None,
        }
        self._over: set = set()
        self._ledger: Optional[LedgerWriter] = None
        self._gauges = {
            res: _reg.REGISTRY.topk_gauge(
                f"gol_tpu_usage_{res}", _HELP[res],
                label="principal", cap=USAGE_TOPK,
            ) for res in RESOURCES
        }
        self._over_gauge = _reg.gauge(
            "gol_tpu_usage_over_budget",
            "Principals currently past a soft usage budget (never "
            "enforced; alert-rule food)",
        )
        _reg.REGISTRY.track_entity_series(
            "principal", *(f"gol_tpu_usage_{r}" for r in RESOURCES),
            topk=True,
        )

    # -- charging --

    def charge(self, principal: str, **amounts: float) -> None:
        """Attribute resources to one principal. Unknown keyword keys
        are rejected loudly (the vocabulary is the contract every
        surface shares)."""
        updated = {}
        with self._lock:
            tot = self._totals.get(principal)
            if tot is None:
                tot = self._totals[principal] = dict.fromkeys(
                    RESOURCES, 0.0)
            pend = self._pending.setdefault(principal, {})
            for res, v in amounts.items():
                if res not in tot:
                    raise ValueError(f"unknown resource {res!r}")
                v = float(v)
                if not v:
                    continue
                tot[res] += v
                pend[res] = pend.get(res, 0.0) + v
                self._grand[res] += v
                updated[res] = tot[res]
            over_n = self._update_budget_locked(principal, tot)
        for res, v in updated.items():
            self._gauges[res].set_child(principal, v)
        if over_n is not None:
            self._over_gauge.set(over_n)

    def _update_budget_locked(self, principal: str,
                              tot: Dict[str, float]) -> Optional[int]:
        bf, bb = self._budgets["flops"], self._budgets["bytes"]
        over = ((bf is not None and tot["flops"] > bf)
                or (bb is not None and tot["wire_bytes"] > bb))
        if over == (principal in self._over):
            return None
        if over:
            self._over.add(principal)
        else:
            self._over.discard(principal)
        return len(self._over)

    def charge_bucket(self, principals: Sequence[str],
                      weights: Optional[Sequence[float]], *,
                      seconds: float = 0.0, flops: float = 0.0,
                      turns: int = 0, what: str = "bucket") -> None:
        """Split ONE measured shared dispatch (S tenants, one vmapped
        program) across its tenants: activity-weighted when `weights`
        are given (per-slot changed-word counts), equal shares
        otherwise. Turns are NOT split — lockstep buckets advance
        every tenant by the full chunk. Conservation-checked."""
        if not principals:
            return
        sec_shares = split_shares(seconds, weights, len(principals))
        flop_shares = split_shares(flops, weights, len(principals))
        check_conservation(seconds, sec_shares, what)
        check_conservation(flops, flop_shares, what)
        for p, ds, fl in zip(principals, sec_shares, flop_shares):
            self.charge(p, dispatch_seconds=ds, flops=fl, turns=turns)

    # -- prices (the PR 9 cost model) --

    def set_price(self, program: str, cost: dict) -> None:
        """Record one program's `cost_of` result as the per-call price
        used for modeled-FLOPs attribution (`publish_cost` feeds this;
        bucket programs key as `bucket.step:<WxH/rule>`)."""
        if not cost or "error" in cost:
            return
        with self._lock:
            self._prices[program] = {
                "flops": float(cost.get("flops", 0.0)),
                "bytes_accessed": float(cost.get("bytes_accessed", 0.0)),
            }

    def price_flops(self, program: str) -> float:
        """Modeled FLOPs per call of `program`; a bucket-specific key
        falls back to the generic program family, then 0 (no cost
        probes = no modeled FLOPs, never a guess)."""
        with self._lock:
            p = self._prices.get(program)
            if p is None and ":" in program:
                p = self._prices.get(program.split(":", 1)[0])
        return p["flops"] if p else 0.0

    # -- budgets --

    def set_budgets(self, flops: Optional[float] = None,
                    bytes: Optional[float] = None) -> None:
        with self._lock:
            self._budgets["flops"] = (
                float(flops) if flops is not None else None)
            self._budgets["bytes"] = (
                float(bytes) if bytes is not None else None)

    # -- lifecycle --

    def forget(self, principal: str) -> None:
        """Drop one principal's live view (session destroyed / peer
        detached): evicts its TopK children through the registry's
        shared helper and its totals row from `/usage`. Pending
        ledger deltas survive — the final flush still persists them;
        history stays in the ledger."""
        with self._lock:
            self._totals.pop(principal, None)
            self._over.discard(principal)
            over_n = len(self._over)
        _reg.REGISTRY.evict_entity("principal", principal)
        self._over_gauge.set(over_n)

    def configure_ledger(self, directory: str, *,
                         max_segment_bytes: int = 4 << 20,
                         flush_secs: float = 1.0) -> None:
        """Arm the crash-safe ledger (CLI serve paths: <out>/usage).
        Idempotent per directory; replaces a previous writer."""
        if self._ledger is not None:
            self._ledger.close()
        self._ledger = LedgerWriter(
            directory, self._drain_pending,
            max_segment_bytes=max_segment_bytes, flush_secs=flush_secs,
        )

    def _drain_pending(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            pending, self._pending = self._pending, {}
        return pending

    def close(self) -> None:
        if self._ledger is not None:
            self._ledger.close()
            self._ledger = None

    # -- exposition --

    def payload(self) -> dict:
        """The `/usage` JSON: per-principal totals (+ over_budget
        flag), process grand totals (include forgotten principals —
        the conservation acceptance compares these against the
        process-level metrics), budgets, pid."""
        with self._lock:
            principals = {p: dict(t) for p, t in self._totals.items()}
            grand = dict(self._grand)
            budgets = dict(self._budgets)
            over = set(self._over)
        for p, t in principals.items():
            t["over_budget"] = p in over
        return {
            "enabled": True,
            "pid": os.getpid(),
            "principals": principals,
            "totals": grand,
            "budgets": budgets,
            "over_budget": sorted(over),
        }


# --- module plane --------------------------------------------------------

#: One attribute read gates every call site: `meter()` answers None
#: when the plane is off (`GOL_TPU_ACCOUNTING=0`) — zero wrappers.
_METER: Optional[Meter] = (
    Meter() if os.environ.get("GOL_TPU_ACCOUNTING", "1") != "0" else None
)


def enabled() -> bool:
    return _METER is not None


def meter() -> Optional[Meter]:
    return _METER


def set_enabled(on: bool = True) -> None:
    """Programmatic switch (the bench's meter-on/off A/B): enabling
    creates a fresh meter; disabling closes the ledger and drops it —
    call sites see None and skip all metering."""
    global _METER
    if on and _METER is None:
        _METER = Meter()
    elif not on and _METER is not None:
        _METER.close()
        _METER = None


def charge(principal: str, **amounts: float) -> None:
    m = _METER
    if m is not None:
        m.charge(principal, **amounts)


def configure(out_dir: Optional[str] = None,
              budget_flops: Optional[float] = None,
              budget_bytes: Optional[float] = None) -> None:
    """CLI arming: ledger under `<out_dir>/usage`, soft budgets. A
    no-op when the plane is disabled (zero ledger I/O). The ledger's
    final drain is registered atexit, so a graceful shutdown persists
    the last partial flush window (a SIGKILL loses at most it — the
    crash-safety acceptance)."""
    m = _METER
    if m is None:
        return
    if budget_flops is not None or budget_bytes is not None:
        m.set_budgets(flops=budget_flops, bytes=budget_bytes)
    if out_dir is not None:
        m.configure_ledger(os.path.join(out_dir, "usage"))
        import atexit

        atexit.register(ledger_close)


def ledger_close() -> None:
    m = _METER
    if m is not None:
        m.close()


def payload() -> dict:
    """The `/usage` endpoint body; an explicit disabled shape when the
    plane is off (a scraper must tell 'disabled' from 'idle')."""
    m = _METER
    if m is None:
        return {"enabled": False}
    return m.payload()
