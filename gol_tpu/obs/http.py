"""Metrics HTTP sidecar — `/metrics`, `/healthz`, `/vars`, `/trace`,
`/flightrecorder`, `/alerts` on a live engine.

Opt-in (`--metrics-port` in the CLI, or `MetricsServer(...)` from
library code): a ThreadingHTTPServer on its own daemon thread serving

- `/metrics`  Prometheus text exposition of the process registry;
- `/vars`     the same registry as a JSON snapshot (the debug-vars
              convention — curl-and-jq friendly);
- `/healthz`  the caller's health dict as JSON, HTTP 200 when its
              "status" is "ok", 503 otherwise — liveness for probes
              that don't parse metrics;
- `/trace`    the recent span window of the process tracer
              (gol_tpu.obs.tracing) as Chrome-trace JSON — save it and
              feed `python -m gol_tpu.obs.report merge`;
- `/flightrecorder`  the live black box (gol_tpu.obs.flight): recent
              lifecycle notes, metric deltas, spans and the current
              state snapshot — what a crash dump WOULD contain, for a
              process that is still alive;
- `/alerts`   the freshness plane's SLO evaluator state
              (gol_tpu.obs.freshness, CLI --alert-rules): every rule
              with its ok/pending/firing state and last value, plus
              the firing count — sane (empty rules, firing 0) when no
              rules are loaded;
- `/usage`    the accounting plane's per-principal usage snapshot
              (gol_tpu.obs.accounting): dispatch seconds, modeled
              FLOPs, host encode seconds, wire bytes and queue
              occupancy per tenant, process totals, budget state —
              `{"enabled": false}` under GOL_TPU_ACCOUNTING=0, so a
              biller can tell "disabled" from "idle";
- `/query`    (collector sidecars only — `tsdb=` was passed) the
              history plane's range-query API:
              `?expr=rate(family)&start=&end=&step=[&source=]`,
              epoch-second bounds (a value starting with "-" is
              relative to now), grammar = the alert rules' aggs plus
              `delta`; 404 with an explicit body elsewhere;
- `/history`  (collector sidecars only) per-source window snapshots
              the console's `--since` mode renders: `?since=SECS`.

With the plane disabled (`GOL_TPU_METRICS=0`) the last two return an
explicit `{"enabled": false}` payload so a scraper can tell "disabled"
from "idle".

The sidecar runs entirely off the engine's threads: a scrape can never
stall a dispatch, and a wedged engine still answers (that is the point
— the old AliveCellsCount ticker was the ONLY live signal, and it dies
with the event stream). Stdlib only, loopback by default; non-loopback
binds should sit behind the same network controls as `--serve`.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from gol_tpu.obs.registry import REGISTRY, Registry

__all__ = ["MetricsServer"]


class MetricsServer:
    """Serve one registry (default: the process-global one) over HTTP.

    `health` is an optional zero-arg callable returning a JSON-able
    dict; it is invoked per `/healthz` request from the HTTP thread, so
    it must be cheap and must not touch the device (Engine.health and
    EngineServer.health read only host-side committed state).

    `alerts` is an optional `freshness.AlertEvaluator`: the sidecar
    OWNS it — `start()` starts its evaluation thread, `close()` stops
    it — and `/alerts` serves its JSON state. Without one, `/alerts`
    answers the explicit empty shape (a scraper must be able to tell
    "no rules configured" from 404-means-old-build).

    `tsdb` is an optional `tsdb.TSDB` (collector processes): `/query`
    and `/history` serve its range queries; without one they 404 with
    an explicit "no history store" body. `remote` is an optional
    `collector.RemoteWriter`, owned like `alerts` (started/stopped
    with the sidecar) — the `--remote-write` flag's plumbing."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 registry: Optional[Registry] = None,
                 health: Optional[Callable[[], dict]] = None,
                 alerts=None, tsdb=None, remote=None):
        reg = registry if registry is not None else REGISTRY
        self.alerts = alerts
        self.tsdb = tsdb
        self.remote = remote
        srv = self  # the handler closes over the sidecar instance

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # no access-log spam on stderr
                pass

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._reply(
                        200, reg.prometheus_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/vars":
                    self._reply(
                        200, json.dumps(reg.snapshot(), indent=2).encode(),
                        "application/json",
                    )
                elif path == "/trace":
                    from gol_tpu.obs.tracing import trace_payload

                    self._reply(
                        200, json.dumps(trace_payload()).encode(),
                        "application/json",
                    )
                elif path == "/flightrecorder":
                    from gol_tpu.obs import flight

                    self._reply(
                        200,
                        json.dumps(flight.payload(), indent=1).encode(),
                        "application/json",
                    )
                elif path == "/alerts":
                    ev = srv.alerts
                    body = (ev.payload() if ev is not None
                            else {"rules": [], "firing": 0})
                    self._reply(200, json.dumps(body, indent=1).encode(),
                                "application/json")
                elif path == "/usage":
                    from gol_tpu.obs import accounting

                    self._reply(
                        200,
                        json.dumps(accounting.payload(),
                                   indent=1).encode(),
                        "application/json",
                    )
                elif path in ("/query", "/history"):
                    db = srv.tsdb
                    if db is None:
                        self._reply(
                            404,
                            json.dumps({"error": "no history store "
                                        "(not a --collector sidecar)"}
                                       ).encode(),
                            "application/json")
                        return
                    import time as _time
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)

                    def _t(name, default):
                        raw = q.get(name, [None])[0]
                        if raw is None:
                            return default
                        v = float(raw)
                        # "-60" means "60 s before now" — relative
                        # bounds save every caller a clock read.
                        return _time.time() + v if raw.startswith("-") \
                            else v
                    try:
                        if path == "/history":
                            body = db.history_payload(
                                float(q.get("since", ["60"])[0]))
                        else:
                            body = db.query(
                                q.get("expr", [""])[0],
                                _t("start", _time.time() - 300.0),
                                _t("end", _time.time()),
                                float(q.get("step", ["5"])[0]),
                                source=q.get("source", [None])[0],
                            )
                    except (ValueError, TypeError) as e:
                        self._reply(
                            400, json.dumps({"error": str(e)}).encode(),
                            "application/json")
                        return
                    self._reply(200, json.dumps(body).encode(),
                                "application/json")
                elif path == "/healthz":
                    try:
                        info = dict(health()) if health is not None \
                            else {"status": "ok"}
                    except Exception as e:  # a broken probe is "down"
                        info = {"status": "error", "error": repr(e)}
                    code = 200 if info.get("status") == "ok" else 503
                    self._reply(code, json.dumps(info).encode(),
                                "application/json")
                else:
                    self._reply(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        #: (host, port) actually bound — port 0 requests an ephemeral one.
        self.address = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="gol-metrics-http", daemon=True,
        )

    def start(self) -> "MetricsServer":
        self._thread.start()
        if self.alerts is not None:
            self.alerts.start()
        if self.remote is not None:
            self.remote.start()
        return self

    def close(self) -> None:
        if self.remote is not None:
            self.remote.close()
        if self.alerts is not None:
            self.alerts.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
