"""Synthetic freshness canary — measure what a user would see.

Servers REPORT their peers' turn age (gol_tpu.obs.freshness), but a
fleet view built only from what servers claim has a blind spot: a tier
that stopped accepting, a gateway mangling frames, a recording pump
wedged at its first keyframe all look healthy from the inside. The
canary closes it by BEING a user:

    python -m gol_tpu.obs.canary HOST:PORT [--metrics-port P] ...

attaches ONE real batching observer at any tier — a root engine
server, a relay leaf, the WebSocket gateway (`--ws`), or a replay
server — runs the ordinary apply path (board sync, vectorized FBATCH
raster advance), and continuously publishes the MEASURED end-to-end
applied-turn age:

- gol_tpu_canary_turn_age_seconds    histogram of sampled ages
- gol_tpu_canary_samples_total       sampling heartbeat
- gol_tpu_canary_info{target,transport}  identity (value 1)
- gol_tpu_client_turn_age_seconds    the live gauge (the ordinary
  client freshness plumbing — obs.console's AGE column reads it)

With `--metrics-port` the canary is one more sidecar the fleet console
scrapes, so the fan-out view carries a measured freshness row next to
the servers' claimed ones. `--duration` + `--max-age` make it a CI
probe: run for N seconds, exit nonzero when the p95 sampled age
exceeds the SLO (or the link never syncs / is lost) —
scripts/freshness_smoke.sh drives exactly that against a live tree and
a replay server.
"""

from __future__ import annotations

import argparse
import base64
import contextlib
import json
import os
import socket
import sys
import threading
import time
from typing import Optional

from gol_tpu import obs
from gol_tpu.obs.freshness import ClientFreshness, sane_lag

__all__ = ["CanaryStats", "WSObserver", "main", "run_canary"]


class _CanaryMetrics:
    def __init__(self):
        self.age = obs.histogram(
            "gol_tpu_canary_turn_age_seconds",
            "End-to-end applied-turn age MEASURED by a real attached "
            "observer (the freshness canary) — what a user sees, not "
            "what servers claim",
        )
        self.samples = obs.counter(
            "gol_tpu_canary_samples_total",
            "Canary sampling sweeps completed",
        )


_METRICS = _CanaryMetrics()


def _publish_info(target: str, transport: str) -> None:
    obs.gauge(
        "gol_tpu_canary_info",
        "Canary identity (value 1): the endpoint it observes and the "
        "transport it uses",
        {"target": target, "transport": transport},
    ).set(1)


class CanaryStats:
    """Sampled age series + summary (the --json payload). The raw
    sample window is BOUNDED (the run-until-interrupted watchdog mode
    must not grow RSS forever — the EventQueue drain's reasoning): the
    summary quantiles cover the most recent window, while the
    histogram metric and `count` keep the full-run totals."""

    WINDOW = 100_000

    def __init__(self):
        import collections

        self.ages: "collections.deque[float]" = collections.deque(
            maxlen=self.WINDOW)
        self.count = 0

    def add(self, age: float) -> None:
        self.ages.append(age)
        self.count += 1
        _METRICS.age.observe(age)
        _METRICS.samples.inc()

    def summary(self) -> dict:
        if not self.ages:
            return {"samples": 0}
        s = sorted(self.ages)
        return {
            "samples": self.count,
            "last_s": round(self.ages[-1], 6),
            "mean_s": round(sum(s) / len(s), 6),
            "p95_s": round(s[min(len(s) - 1, int(0.95 * len(s)))], 6),
            "max_s": round(s[-1], 6),
        }


class WSObserver:
    """A real browser-shaped observer: RFC-6455 client against the
    relay's WS gateway, applying the IDENTICAL binary frame payloads a
    TCP observer gets (no length prefix — WS frames self-delimit).
    Server pings ARE the heartbeat beacons and carry the committed
    turn, so the head clock advances even while the stream idles."""

    def __init__(self, host: str, port: int, *,
                 secret: Optional[str] = None,
                 session: Optional[str] = None,
                 batch_turns: int = 256, timeout: float = 30.0):
        import numpy as np  # the apply path is vectorized

        from gol_tpu.relay import ws as wsproto

        self._np = np
        self._ws = wsproto
        self.freshness = ClientFreshness()
        self.board = None
        self.synced = threading.Event()
        self.closed = threading.Event()
        #: Set only on an ERROR teardown (protocol violation, socket
        #: death) — a clean server close is the stream ending, not a
        #: canary failure.
        self.lost = threading.Event()
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._sock.settimeout(timeout)
        self._send_lock = threading.Lock()
        key = base64.b64encode(os.urandom(16)).decode("ascii")
        req = (
            f"GET / HTTP/1.1\r\nHost: {host}:{port}\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n"
            f"Sec-WebSocket-Protocol: {wsproto.SUBPROTOCOL}\r\n\r\n"
        )
        self._sock.sendall(req.encode("ascii"))
        # Byte-wise head read so no WS frame byte is ever swallowed
        # into a throwaway buffer (the head is ~200 bytes, once).
        head = bytearray()
        while not head.endswith(b"\r\n\r\n"):
            b = self._sock.recv(1)
            if not b:
                raise ConnectionError("gateway closed during upgrade")
            head.extend(b)
            if len(head) > 65536:
                raise ConnectionError("oversized upgrade response")
        status = bytes(head).split(b"\r\n", 1)[0]
        if b" 101 " not in status + b" ":
            raise ConnectionError(
                f"gateway refused the upgrade: {status!r}"
            )
        hello = {"t": "hello", "want_flips": True, "binary": True,
                 "hb": True, "role": "observe", "batch": batch_turns}
        if session is not None:
            hello["session"] = session
        if secret is not None:
            hello["secret"] = secret
        self._send(wsproto.OP_TEXT,
                   json.dumps(hello, separators=(",", ":")).encode())
        self._thread = threading.Thread(target=self._reader,
                                        name="gol-canary-ws",
                                        daemon=True)
        self._thread.start()

    def _send(self, op: int, payload: bytes) -> None:
        # Client frames MUST be masked (RFC 6455; the gateway fails
        # the connection otherwise).
        frame = self._ws.encode_frame(op, payload, mask=True)
        with self._send_lock:
            self._sock.sendall(frame)

    def wait_sync(self, timeout: float = 60.0) -> bool:
        return self.synced.wait(timeout)

    def turn_age(self) -> float:
        return self.freshness.age()

    def _on_msg(self, msg: dict) -> None:
        from gol_tpu.distributed import wire
        from gol_tpu.distributed.client import apply_fbatch_raster

        np = self._np
        t = msg.get("t")
        if t == "board":
            turn, board = wire.msg_to_board(msg)
            self.board = np.array(board, dtype=np.uint8)
            self.freshness.note_head(turn)
            self.freshness.note_applied(turn)
            self.synced.set()
        elif t == "fbatch" and self.board is not None:
            last = int(msg["first_turn"]) + int(msg["k"]) - 1
            apply_fbatch_raster(self.board, msg,
                                self.freshness.applied_turn)
            lag = sane_lag(msg.get("ts"))
            self.freshness.note_head(
                last, None if lag is None else time.time() - lag
            )
            self.freshness.note_applied(last)
        elif t == "hb":
            self.freshness.note_head(msg.get("turn"))
        elif t == "ev" and msg.get("k") == "turn":
            self.freshness.note_head(msg.get("turn"))
            self.freshness.note_applied(msg.get("turn"))

    def _reader(self) -> None:
        from gol_tpu.distributed import wire

        wsproto = self._ws
        try:
            while True:
                op, payload = wsproto.read_message(self._sock,
                                                   require_mask=False)
                if op == wsproto.OP_CLOSE:
                    return
                if op == wsproto.OP_PING:
                    # The beacon: payload is the committed turn as
                    # ASCII digits — head evidence AND the liveness
                    # pong in one.
                    with contextlib.suppress(ValueError, TypeError):
                        self.freshness.note_head(
                            int((payload or b"0").decode("ascii"))
                        )
                    self._send(wsproto.OP_PONG, payload or b"")
                    continue
                if op == wsproto.OP_PONG or not payload:
                    continue
                with contextlib.suppress(wire.WireError, ValueError,
                                         KeyError):
                    self._on_msg(wire.parse_payload(payload))
        except Exception:
            # Link death (vs the clean OP_CLOSE return above) is a
            # probe FAILURE the sampler must report.
            self.lost.set()
        finally:
            self.closed.set()

    def close(self) -> None:
        with contextlib.suppress(OSError):
            self._sock.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self._sock.close()
        self.closed.set()


def run_canary(target: str, *, session: Optional[str] = None,
               secret: Optional[str] = None, batch_turns: int = 256,
               interval: float = 1.0, duration: Optional[float] = None,
               max_age: Optional[float] = None, use_ws: bool = False,
               as_json: bool = False, out=None) -> int:
    """Attach, sample, publish; returns the process exit code (0 ok,
    1 attach failure, 2 link lost, 3 SLO exceeded)."""
    out = out or sys.stdout
    host, _, port_s = target.rpartition(":")
    host = host or "127.0.0.1"
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(
            f"bad canary target {target!r} — expected HOST:PORT"
        ) from None
    transport = "ws" if use_ws else "tcp"
    _publish_info(f"{host}:{port}", transport)
    stats = CanaryStats()
    if use_ws:
        watcher = WSObserver(host, port, secret=secret, session=session,
                             batch_turns=batch_turns)
    else:
        from gol_tpu.distributed.client import Controller

        watcher = Controller(
            host, port, want_flips=True, secret=secret, observe=True,
            batch=True, batch_turns=batch_turns,
            batch_flip_events=False, session=session,
        )
        # Drain the event stream: the Controller's EventQueue is
        # unbounded and the canary reads only ages — on a 10^5 turns/s
        # tier the undrained TurnComplete objects would grow RSS until
        # the watchdog process itself is the thing that dies.
        def _drain():
            for _ in watcher.events:
                pass

        threading.Thread(target=_drain, name="gol-canary-drain",
                         daemon=True).start()
    try:
        if not watcher.wait_sync(60.0):
            print("canary: no board sync from the target (attach "
                  "failed or run already over)", file=sys.stderr)
            return 1
        deadline = (time.monotonic() + duration
                    if duration is not None else None)
        link_lost = False
        while deadline is None or time.monotonic() < deadline:
            time.sleep(max(0.05, interval))
            # A LOST link (reconnect exhausted, policy-rejected,
            # protocol death) is a probe failure; a cleanly ended
            # stream (bye — the run or recording is over) just stops
            # the sampling and the SLO gate judges what was measured.
            link_lost = watcher.lost.is_set()
            ended = link_lost or (watcher.closed.is_set() if use_ws
                                  else watcher.events.closed)
            age = watcher.turn_age()
            stats.add(age)
            # The live gauge for BOTH transports: the Controller sets
            # it per message, but the WS observer has no Controller —
            # without this a --ws canary's console row shows no AGE.
            obs.gauge("gol_tpu_client_turn_age_seconds").set(
                round(age, 6))
            if not as_json:
                out.write(
                    f"canary {host}:{port} [{transport}] "
                    f"applied turn {watcher.freshness.applied_turn} "
                    f"head {watcher.freshness.head()} "
                    f"age {age * 1e3:.1f}ms\n"
                )
                out.flush()
            if ended:
                break
        summary = {
            "target": f"{host}:{port}", "transport": transport,
            "applied_turn": watcher.freshness.applied_turn,
            "head_turn": watcher.freshness.head(),
            "age": stats.summary(),
        }
        ok = not link_lost
        if max_age is not None:
            p95 = summary["age"].get("p95_s")
            ok = ok and p95 is not None and p95 <= max_age
            summary["max_age_s"] = max_age
        summary["lost"] = link_lost
        summary["ok"] = ok
        if as_json:
            out.write(json.dumps(summary, indent=1) + "\n")
        else:
            out.write(f"canary summary: {json.dumps(summary)}\n")
        if link_lost:
            print("canary: link lost", file=sys.stderr)
            return 2
        return 0 if ok else 3
    finally:
        with contextlib.suppress(Exception):
            watcher.close()


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gol_tpu.obs.canary",
        description="synthetic freshness canary: attach a real "
                    "observer at any tier and publish MEASURED "
                    "end-to-end turn age",
    )
    ap.add_argument("target", metavar="HOST:PORT",
                    help="the tier to observe (root server, relay, "
                         "WS gateway with --ws, or replay server)")
    ap.add_argument("--session", default=None, metavar="ID",
                    help="named session on a --sessions/--replay tier")
    ap.add_argument("--secret", default=os.environ.get("GOL_SECRET"),
                    metavar="TOKEN", help="shared attach secret")
    ap.add_argument("--batch-turns", type=int, default=256,
                    dest="batch_turns", metavar="K",
                    help="negotiated k-turn batch frames (default 256)")
    ap.add_argument("--interval", type=float, default=1.0, metavar="SEC",
                    help="sampling cadence (default 1)")
    ap.add_argument("--duration", type=float, default=None, metavar="SEC",
                    help="stop after SEC seconds and print the summary "
                         "(default: run until interrupted)")
    ap.add_argument("--max-age", type=float, default=None,
                    dest="max_age", metavar="SEC",
                    help="CI gate: exit 3 when the p95 sampled age "
                         "exceeds SEC")
    ap.add_argument("--ws", action="store_true",
                    help="attach over the RFC-6455 WebSocket gateway "
                         "instead of raw TCP")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="suppress per-sample lines; print one JSON "
                         "summary")
    ap.add_argument("--metrics-port", type=int, default=None,
                    dest="metrics_port", metavar="PORT",
                    help="serve this canary's own /metrics sidecar "
                         "(0 = ephemeral, printed) so the fleet "
                         "console scrapes the measured freshness")
    ap.add_argument("--metrics-host", default="127.0.0.1",
                    metavar="HOST")
    ap.add_argument("--remote-write", default=None, dest="remote_write",
                    metavar="HOST:PORT",
                    help="with --metrics-port: push the measured "
                         "freshness series to the history-plane "
                         "collector at HOST:PORT — what the fleet "
                         "controller's scale rule reads back as "
                         "canary turn-age HISTORY "
                         "(docs/OBSERVABILITY.md 'History plane')")
    args = ap.parse_args(argv)

    if args.remote_write is not None and args.metrics_port is None:
        ap.error("--remote-write requires --metrics-port (the writer "
                 "rides the metrics sidecar)")

    from gol_tpu.obs import tracing

    tracing.set_process_label("canary")
    metrics = None
    if args.metrics_port is not None:
        from gol_tpu.obs.http import MetricsServer

        metrics = MetricsServer(args.metrics_host, args.metrics_port)
        if args.remote_write is not None:
            from gol_tpu.obs.collector import RemoteWriter

            metrics.remote = RemoteWriter(
                args.remote_write,
                source=f"canary@{metrics.address[0]}:"
                       f"{metrics.address[1]}",
                secret=args.secret,
            )
            print(f"remote-write to {args.remote_write} "
                  f"(source {metrics.remote.source})")
        metrics.start()
        print(f"metrics serving on http://{metrics.address[0]}:"
              f"{metrics.address[1]}/metrics")
    try:
        return run_canary(
            args.target, session=args.session, secret=args.secret,
            batch_turns=args.batch_turns, interval=args.interval,
            duration=args.duration, max_age=args.max_age,
            use_ws=args.ws, as_json=args.as_json,
        )
    except KeyboardInterrupt:
        return 0
    except (ConnectionError, OSError, ValueError) as e:
        # ValueError covers a malformed target spec — a typo'd
        # HOST:PORT in a CI script gets the diagnostic and exit 1,
        # never a raw traceback.
        print(f"canary: cannot attach to {args.target}: {e}",
              file=sys.stderr)
        return 1
    finally:
        if metrics is not None:
            metrics.close()


if __name__ == "__main__":
    sys.exit(main())
