"""Named-span tracer — one timeline for the whole session.

`utils/trace.py`'s Timeline records ONE kind of span (engine dispatches)
for ONE consumer (the profiling harness). This module is the general
form: any layer records named spans and instant events into a bounded
process-global ring, and the whole ring exports as Chrome-trace JSON
(the `chrome://tracing` / Perfetto format — the stand-in for the
reference's `go tool trace` artifact, but spanning every hop of a
distributed session instead of one process's goroutines).

Record shape (host-side, wall-anchored):

- a SPAN is (name, cat, ts, dur, tid, args) — `ts` is `time.time()` at
  enter (so two processes' dumps share a timebase up to clock offset),
  `dur` measured with `perf_counter` deltas;
- an EVENT is the same minus `dur` (Chrome "instant" phase) — used for
  per-turn wire correlation (`turn.emit` / `turn.apply`) and lifecycle
  marks (reconnects, evictions, clock sync).

Design constraints, matching `obs.registry`:

- **Pure stdlib** — the flight recorder and the analysis layer must be
  able to feed/read this with zero dependency cost.
- **Single-writer-per-thread ring.** Appends are one `deque.append`
  (atomic under the GIL, the Timeline argument); readers snapshot.
  Past `capacity` the OLDEST records are evicted; `dropped` counts the
  truncation.
- **Zero-cost when disabled.** The tracer follows the registry's
  enablement (`GOL_TPU_METRICS=0` / `obs.set_enabled(False)`): every
  record call returns behind one flag read, `span()` hands back a
  shared null context manager, and the ring itself is allocated lazily
  on the first record — a disabled process never allocates it at all.
- **Never in a jitted path.** The `obs-in-jit` analysis check extends
  to this module: a span enter/exit under trace would record once per
  COMPILE, not per step.

Cross-process correlation: the distributed handshake's clock probe
(docs/OBSERVABILITY.md) estimates this process's wall-clock offset to
its server peer; `set_clock_offset` stores it, the export carries it in
`metadata`, and `python -m gol_tpu.obs.report merge` shifts the dump
onto the peer's timebase when joining the two files.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

import importlib

from gol_tpu.obs.registry import atomic_write_text

# The live module object (the package __init__ rebinds the attribute
# `gol_tpu.obs.registry` to its same-named convenience FUNCTION, so an
# `import ... as` spelling would grab that instead): every record call
# reads `_registry._ENABLED` — the one switch `set_enabled` flips.
_registry = importlib.import_module("gol_tpu.obs.registry")

__all__ = [
    "TRACER",
    "Tracer",
    "add_span",
    "clock_offset",
    "event",
    "set_clock_offset",
    "set_metadata",
    "set_process_label",
    "span",
    "trace_payload",
]

#: Ring capacity: ~64k records keep the recent minutes of a busy
#: distributed session (a watched 512² run records a handful of spans
#: per turn) in a few MB of tuples.
DEFAULT_CAPACITY = 65_536


class _NullSpan:
    """The disabled-path context manager — one shared instance, no
    allocation per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Live span: anchors wall time at enter, measures dur with
    perf_counter, records itself on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_wall", "_tick")

    def __init__(self, tracer, name, cat, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._wall = time.time()
        self._tick = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer.add_span(
            self._name, self._cat, self._wall,
            time.perf_counter() - self._tick, self._args,
        )
        return False


class Tracer:
    """Bounded ring of spans/events with Chrome-trace export.

    One process-global instance (`TRACER`) serves the whole package;
    tests may build private ones. All mutation paths check the
    registry's live enablement flag, so `obs.set_enabled(False)` (or
    `GOL_TPU_METRICS=0` at import) silences this plane too.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        #: Allocated on the FIRST record — a disabled process never
        #: pays for the ring (satellite contract: no ring allocations
        #: on the hot path with metrics off).
        self._ring: "Optional[collections.deque]" = None
        self._recorded = 0
        #: Wall-clock offset (seconds) to the session's reference
        #: timebase (the server peer): server_time ≈ local_time +
        #: offset. None until a clock probe measured it.
        self.clock_offset_seconds: Optional[float] = None
        #: Human label for this process in merged timelines
        #: ("serve" / "connect" / "local" — the CLI sets it).
        self.process_label: str = ""
        #: Extra metadata keys carried verbatim in the export (e.g.
        #: the device plane's profile-capture directory) — merged
        #: reports surface them next to the timeline.
        self.extra_metadata: dict = {}

    # -- writers (hot path) --

    def _rec(self, record) -> None:
        ring = self._ring
        if ring is None:
            # Lazy, idempotent: two racing first-writers both build a
            # deque; the losing one's record lands in the winner's ring
            # on its next append at worst — bounded-loss, lock-free.
            ring = self._ring = collections.deque(maxlen=self.capacity)
        self._recorded += 1
        ring.append(record)

    def add_span(self, name: str, cat: str, ts: float, dur: float,
                 args: Optional[dict] = None) -> None:
        """Record one completed span: `ts` wall seconds at start,
        `dur` seconds. For callers that already measured (the engine's
        dispatch bookkeeping) — `span()` is the measuring form."""
        if not _registry._ENABLED:
            return
        self._rec(("X", name, cat, ts, dur,
                   threading.get_ident(), args or None))

    def add_event(self, name: str, cat: str, ts: Optional[float] = None,
                  args: Optional[dict] = None) -> None:
        if not _registry._ENABLED:
            return
        self._rec(("i", name, cat,
                   time.time() if ts is None else ts, 0.0,
                   threading.get_ident(), args or None))

    def span(self, name: str, cat: str = "", **args):
        """Context manager recording one span around the enclosed
        block. Returns a shared null manager when tracing is off."""
        if not _registry._ENABLED:
            return _NULL_SPAN
        return _Span(self, name, cat, args or None)

    def event(self, name: str, cat: str = "", **args) -> None:
        self.add_event(name, cat, None, args or None)

    # -- readers --

    @property
    def records(self) -> list:
        return list(self._ring) if self._ring is not None else []

    @property
    def recorded(self) -> int:
        return self._recorded

    @property
    def dropped(self) -> int:
        retained = len(self._ring) if self._ring is not None else 0
        return max(0, self._recorded - retained)

    def clear(self) -> None:
        """Drop every record (tests); totals reset too."""
        self._ring = None
        self._recorded = 0

    def chrome_trace(self, limit: Optional[int] = None) -> dict:
        """The ring as a Chrome-trace dict: `traceEvents` (ts/dur in
        MICROseconds, per the format) plus `metadata` carrying the
        process identity and the measured clock offset — everything
        `gol_tpu.obs.report merge` needs to join two processes' dumps
        onto one corrected timebase. `limit` keeps only the newest N
        records (the flight recorder embeds a bounded tail, not the
        whole 64k ring)."""
        pid = os.getpid()
        events = []
        tids = set()
        records = self.records
        if limit is not None and len(records) > limit:
            records = records[-limit:]
        for ph, name, cat, ts, dur, tid, args in records:
            tids.add(tid)
            ev = {"name": name, "cat": cat or "gol", "ph": ph,
                  "ts": round(ts * 1e6, 1), "pid": pid, "tid": tid}
            if ph == "X":
                ev["dur"] = round(dur * 1e6, 1)
            else:
                ev["s"] = "p"  # instant scope: process
            if args:
                ev["args"] = dict(args)
            events.append(ev)
        if self.process_label:
            events.insert(0, {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": self.process_label},
            })
        return {
            "traceEvents": events,
            "metadata": {
                "pid": pid,
                "process_label": self.process_label,
                "clock_offset_seconds": self.clock_offset_seconds,
                "recorded": self._recorded,
                "dropped": self.dropped,
                "dumped_at": time.time(),
                **self.extra_metadata,
            },
        }

    def dump(self, path) -> None:
        """Crash-safe Chrome-trace JSON (atomic_write_text)."""
        atomic_write_text(path, json.dumps(self.chrome_trace()))


#: The process-global tracer every gol_tpu layer records into.
TRACER = Tracer()


def span(name: str, cat: str = "", **args):
    return TRACER.span(name, cat, **args)


def event(name: str, cat: str = "", **args) -> None:
    TRACER.add_event(name, cat, None, args or None)


def add_span(name: str, cat: str, ts: float, dur: float,
             args: Optional[dict] = None) -> None:
    TRACER.add_span(name, cat, ts, dur, args)


def set_clock_offset(offset_seconds: float) -> None:
    """Record the measured wall-clock offset to the session's reference
    timebase (server_time - local_time, from the handshake probe)."""
    TRACER.clock_offset_seconds = float(offset_seconds)


def clock_offset() -> Optional[float]:
    return TRACER.clock_offset_seconds


def set_process_label(label: str) -> None:
    TRACER.process_label = str(label)


def set_metadata(key: str, value) -> None:
    """Attach one JSON-able key to the export metadata (e.g. the
    --profile-dir capture path, so merged reports can link it)."""
    TRACER.extra_metadata[str(key)] = value


def trace_payload() -> dict:
    """The `/trace` endpoint body: the recent span window as a Chrome
    trace, or an EXPLICIT disabled payload when the plane is off (a
    scraper must be able to tell "disabled" from "idle")."""
    if not _registry._ENABLED:
        return {"enabled": False,
                "reason": "metrics/tracing disabled "
                          "(GOL_TPU_METRICS=0 or set_enabled(False))"}
    out = TRACER.chrome_trace()
    out["enabled"] = True
    return out
