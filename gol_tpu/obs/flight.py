"""Flight recorder — the crash-surviving black box.

A crashed or evicted process takes its in-memory Timeline and tracer
ring with it; the metrics endpoint dies with the HTTP thread. This
module is the part that SURVIVES the failure it describes: a bounded
process-global ring of recent lifecycle notes (dispatch commits,
reconnects, evictions, invariant violations, redo decisions) plus, at
dump time, the recent tracer spans, the metric deltas since the
recorder was armed, and a caller-provided state snapshot (the engine's
`health()`), written CRASH-ATOMICALLY (`atomic_write_text` — temp file,
fsync, rename) so a dump interrupted by the very failure it records
never leaves a truncated artifact.

Dump triggers (wired by the layers themselves + the CLI):

- SIGTERM               cli.py installs a handler that dumps, then
                        raises KeyboardInterrupt for graceful teardown
- fatal engine error    engine/distributor.py's run() catch-all
- peer eviction         distributed/server.py's heartbeat judge
- reconnect exhaustion  distributed/client.py's ConnectionLost path

Live access: the `/flightrecorder` endpoint on `MetricsServer` serves
`payload()` — the same content the dump would have, for a process that
is still alive.

Enablement follows the registry (`GOL_TPU_METRICS=0` /
`obs.set_enabled(False)`): notes no-op behind one flag read, the ring
is allocated lazily on the first note, and `dump()` writes nothing.
File dumps additionally require a configured directory (`configure`) —
library embedders that never call it get the in-memory ring and the
live endpoint but no surprise files on disk.

Pure stdlib on purpose: `analysis.invariants` notes its violations
here and must stay importable from worker processes at zero cost.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, Optional

import importlib

from gol_tpu.obs.registry import REGISTRY, atomic_write_text

# Live module object — see the twin note in tracing.py (the package
# __init__ shadows the submodule attribute with a function).
_registry = importlib.import_module("gol_tpu.obs.registry")

__all__ = [
    "FLIGHT",
    "FlightRecorder",
    "configure",
    "dump",
    "install_sigterm_handler",
    "note",
    "payload",
    "set_state_provider",
]

#: Ring capacity. Notes are per lifecycle event / per dispatch chunk
#: (≤ kHz), so 4096 entries hold minutes of recent history in well
#: under a MB.
DEFAULT_CAPACITY = 4096

#: Newest tracer records embedded in a dump. Bounded on purpose: a
#: dump can run on latency-sensitive threads (the server's heartbeat
#: judge on eviction, the SIGTERM handler), and serializing + fsyncing
#: the tracer's full 64k ring there would stall beacons for the write;
#: the recent tail is what a post-mortem reads anyway.
SPAN_TAIL = 2048


class FlightRecorder:
    """Bounded note ring + crash-atomic dumps. One process-global
    instance (`FLIGHT`); tests may build private ones."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self._ring: "Optional[collections.deque]" = None
        self._recorded = 0
        self._dir: Optional[str] = None
        #: Counter/gauge values when the recorder was armed — dumps
        #: report the DELTA, so a post-mortem shows what this run did,
        #: not what the process accumulated before `configure`.
        self._baseline: dict = {}
        #: Zero-arg callable returning a JSON-able state snapshot
        #: (Engine.health / EngineServer.health) — captured at dump
        #: time so the artifact pins the committed turn it died at.
        self._state: Optional[Callable[[], dict]] = None
        self._dump_lock = threading.Lock()
        #: Paths of dumps this process wrote (latest last).
        self.dumps: list = []

    # -- writers --

    def note(self, kind: str, **fields) -> None:
        """Record one lifecycle note. Host-side, bounded, GIL-atomic
        append — safe from any thread, no-op when disabled."""
        if not _registry._ENABLED:
            return
        ring = self._ring
        if ring is None:
            ring = self._ring = collections.deque(maxlen=self.capacity)
        self._recorded += 1
        ring.append((time.time(), kind, fields or None))

    # -- configuration --

    def configure(self, directory: Optional[str] = None, *,
                  state: Optional[Callable[[], dict]] = None) -> None:
        """Arm the recorder: where file dumps go (None keeps them off),
        what state snapshot to capture at dump time, and the metric
        baseline deltas are measured from."""
        if directory is not None:
            self._dir = os.fspath(directory)
        if state is not None:
            self._state = state
        if _registry._ENABLED:
            self._baseline = {
                _series_key(m): m.snapshot_value()
                for m in REGISTRY.metrics()
            }

    def set_state_provider(self, state: Callable[[], dict]) -> None:
        self._state = state

    # -- readers / dumps --

    @property
    def entries(self) -> list:
        return list(self._ring) if self._ring is not None else []

    @property
    def dropped(self) -> int:
        retained = len(self._ring) if self._ring is not None else 0
        return max(0, self._recorded - retained)

    def clear(self) -> None:
        """Tests: drop notes, dumps and the baseline."""
        self._ring = None
        self._recorded = 0
        self._baseline = {}
        self.dumps = []

    def _metric_deltas(self) -> dict:
        """Counters as deltas vs the armed baseline, gauges as current
        values, histograms as count deltas — the 'what did THIS run
        do' view a post-mortem wants."""
        out = {}
        for m in REGISTRY.metrics():
            key = _series_key(m)
            now = m.snapshot_value()
            base = self._baseline.get(key)
            if m.kind == "counter":
                out[key] = now - (base if isinstance(base, float) else 0.0)
            elif m.kind == "gauge":
                out[key] = now
            else:  # histogram: the count tells the rate story
                base_n = base["count"] if isinstance(base, dict) else 0
                out[key + ":count"] = now["count"] - base_n
        return out

    def payload(self, reason: Optional[str] = None) -> dict:
        """The black box content as one JSON-able dict — shared by the
        live `/flightrecorder` endpoint (reason None) and file dumps."""
        if not _registry._ENABLED:
            return {"enabled": False,
                    "reason": "metrics/tracing disabled "
                              "(GOL_TPU_METRICS=0 or set_enabled(False))"}
        from gol_tpu.obs.tracing import TRACER

        state = None
        if self._state is not None:
            try:
                state = dict(self._state())
            except Exception as e:  # a broken probe must not kill a dump
                state = {"status": "error", "error": repr(e)}
        return {
            "enabled": True,
            "reason": reason,
            "dumped_at": time.time(),
            "pid": os.getpid(),
            "process_label": TRACER.process_label,
            "clock_offset_seconds": TRACER.clock_offset_seconds,
            "state": state,
            "entries": [
                {"ts": ts, "kind": kind, **(fields or {})}
                for ts, kind, fields in self.entries
            ],
            "dropped": self.dropped,
            "metric_deltas": self._metric_deltas(),
            "spans": TRACER.chrome_trace(limit=SPAN_TAIL)["traceEvents"],
        }

    def dump(self, reason: str, path=None) -> Optional[str]:
        """Write the black box crash-atomically. `path` overrides the
        configured directory; with neither (or disabled), no file is
        written and None returns — safe to call unconditionally from
        failure paths."""
        if not _registry._ENABLED:
            return None
        if path is None:
            if self._dir is None:
                return None
            # The configured directory is usually --out, which the
            # engine only creates at its first snapshot — a dump must
            # not fail because the run died before checkpointing.
            try:
                os.makedirs(self._dir, exist_ok=True)
            except OSError:
                return None
            path = os.path.join(
                self._dir, f"flightrecorder-{os.getpid()}.json"
            )
        path = os.fspath(path)
        # Serialized: SIGTERM-during-eviction must not interleave two
        # writers onto one temp file set.
        with self._dump_lock:
            self.note("flight.dump", reason=reason)
            atomic_write_text(
                path, json.dumps(self.payload(reason), indent=1)
            )
            self.dumps.append(path)
        return path


def _series_key(m) -> str:
    """The registry's own Prometheus series spelling (shared escaping
    included) — baseline/delta keys must line up byte-for-byte with
    `Registry.snapshot()` keys."""
    return f"{m.name}{_registry._fmt_labels(m.labels)}"


#: The process-global black box every gol_tpu layer notes into.
FLIGHT = FlightRecorder()


def note(kind: str, **fields) -> None:
    FLIGHT.note(kind, **fields)


def configure(directory: Optional[str] = None, *,
              state: Optional[Callable[[], dict]] = None) -> None:
    FLIGHT.configure(directory, state=state)


def set_state_provider(state: Callable[[], dict]) -> None:
    FLIGHT.set_state_provider(state)


def payload(reason: Optional[str] = None) -> dict:
    return FLIGHT.payload(reason)


def dump(reason: str, path=None) -> Optional[str]:
    return FLIGHT.dump(reason, path)


_SIGTERM_INSTALLED = False


def install_sigterm_handler() -> bool:
    """Dump the black box the instant SIGTERM lands, then raise
    KeyboardInterrupt so the process's ordinary graceful-shutdown path
    (the CLI catches it around every run mode) still executes. Main
    thread only (signal module contract) and idempotent (in-process
    callers — tests — invoke the CLI repeatedly; handlers must not
    chain onto themselves); returns False where a handler cannot be
    installed instead of breaking embedders."""
    global _SIGTERM_INSTALLED
    import signal

    if threading.current_thread() is not threading.main_thread():
        return False
    if _SIGTERM_INSTALLED:
        return True
    prev = signal.getsignal(signal.SIGTERM)

    def _on_sigterm(signum, frame):
        FLIGHT.dump("sigterm")
        if callable(prev) and prev not in (
            signal.SIG_IGN, signal.SIG_DFL, signal.default_int_handler
        ):
            prev(signum, frame)
        raise KeyboardInterrupt

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # non-main thread race / exotic host
        return False
    _SIGTERM_INSTALLED = True
    return True
