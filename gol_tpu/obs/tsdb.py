"""The history plane's store: a tiny stdlib TSDB for metric samples.

Every other observability surface in gol_tpu is point-in-time: metrics
exist at scrape instants, the alert evaluator judges the current
sample, the controller scales on what it sees *now*. This module is
the memory: per-source, per-series (timestamp, value) history held in
bounded in-memory rings and persisted in crash-atomic, keyframe-indexed
segment logs following the replay plane's recorder discipline
(gol_tpu/replay/log.py) — append + flush per record, torn tails
TOLERATED on read (a SIGKILL mid-write loses at most the half-written
record, never an earlier sample), eviction size-bounded and
oldest-first, never the active segment.

Layout on disk (`<root>/hist-<epoch_millis:016d>.tlog`):

    record  := u32 payload_len, f64 append_walltime, payload
    payload := codec byte (0 = raw, 1 = zlib) + JSON object
    JSON    := {"t":"s","src":S,"ts":T,"s":[[key,value],...]}   sample
             | {"t":"key","state":{src:{key:[ts,value],...}}}   keyframe

Each segment OPENS with a keyframe record carrying the last known
value of every live series, so any segment is interpretable on its
own: after older segments are evicted, a resume still answers
"current value" queries for slow-moving series that have not re-sent
since. Samples carry ABSOLUTE values (the wire's delta encoding is in
the series *set*, not the values), so replay order is the only state
and a dropped record can never corrupt later ones.

The query half implements the alert grammar's aggregations —
`sum` (bare family), `max`, `min`, `avg`, `rate`, `delta`, and
bucket-merge `p50/p95/p99` built on the registry's shared
`quantile_from_buckets` / `merge_cumulative_buckets` — over
[start, end] at a fixed step. Stdlib only, like every obs module.
"""

from __future__ import annotations

import importlib
import json
import logging
import os
import re
import struct
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Tuple

_reg = importlib.import_module("gol_tpu.obs.registry")

__all__ = [
    "TSDB",
    "eval_expr",
    "parse_expr",
    "read_records",
    "scan_segments",
]

log = logging.getLogger(__name__)

#: Record header: payload bytes, append wall-clock seconds (the same
#: shape the replay log uses — u32 length, f64 timestamp).
_REC = struct.Struct("<Id")
_SEG = re.compile(r"^hist-(\d{16})\.tlog$")
#: One record's decoded-payload ceiling — far above any real keyframe
#: (thousands of series at ~100 bytes each); a length past it reads as
#: corruption, i.e. the torn tail.
_REC_RAW_MAX = 8 << 20

DEFAULT_RETENTION_SECS = 3600.0
DEFAULT_MAX_BYTES = 64 << 20
DEFAULT_SEGMENT_BYTES = 4 << 20
#: Per-series in-memory point ring.
DEFAULT_MAX_POINTS = 4096
#: Per-source series-cardinality bound (a hostile or buggy writer
#: inventing label values must not grow memory without bound).
DEFAULT_MAX_SERIES = 8192

_AGGS = ("sum", "max", "min", "avg", "rate", "delta",
         "p50", "p95", "p99")
_EXPR_RE = re.compile(
    r"^(?:(?P<agg>[a-z]\w*)\((?P<fam1>[A-Za-z_:][\w:]*)\)"
    r"|(?P<fam2>[A-Za-z_:][\w:]*))$"
)


def parse_expr(expr: str) -> Tuple[str, str]:
    """`family` or `agg(family)` -> (agg, family); the alert rule
    grammar's left-hand side plus `delta` (bare family == sum, exactly
    like the rules). ValueError on anything else — the /query endpoint
    maps that to HTTP 400."""
    m = _EXPR_RE.match(expr.strip())
    if not m:
        raise ValueError(f"cannot parse query expr {expr!r}")
    agg = m.group("agg") or "sum"
    if agg not in _AGGS:
        raise ValueError(
            f"unknown aggregation {agg!r} (one of {', '.join(_AGGS)})"
        )
    return agg, m.group("fam1") or m.group("fam2")


def _pack(obj: dict) -> bytes:
    raw = json.dumps(obj, separators=(",", ":")).encode()
    if len(raw) > 256:
        z = zlib.compress(raw, 1)
        if len(z) < len(raw):
            return b"\x01" + z
    return b"\x00" + raw


def _unpack(payload: bytes) -> dict:
    """Decode one record payload; raises ValueError on anything
    malformed (the reader treats that as the torn tail)."""
    if not payload:
        raise ValueError("empty record payload")
    codec, data = payload[0], payload[1:]
    if codec == 1:
        d = zlib.decompressobj()
        data = d.decompress(data, _REC_RAW_MAX)
        if d.unconsumed_tail or not d.eof:
            raise ValueError("oversized or truncated record blob")
    elif codec != 0:
        raise ValueError(f"unknown record codec {codec}")
    obj = json.loads(data.decode())
    if not isinstance(obj, dict):
        raise ValueError("record payload is not an object")
    return obj


def scan_segments(root: str) -> List[Tuple[int, str]]:
    """Sorted [(start_millis, path)] — tolerant of a missing dir."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _SEG.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    out.sort()
    return out


def read_records(path: str):
    """Yield decoded record dicts until EOF or the torn tail. Identical
    discipline to the replay log's reader: a header whose length
    overruns the file (or fails to decode) is the half-written tail of
    a crash — stop there, never raise, never yield garbage."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return
    off = 0
    while off + _REC.size <= len(blob):
        n, ts = _REC.unpack_from(blob, off)
        if n > _REC_RAW_MAX or off + _REC.size + n > len(blob):
            break  # torn tail: the crash frontier
        try:
            obj = _unpack(blob[off + _REC.size:off + _REC.size + n])
        except (ValueError, zlib.error, UnicodeDecodeError):
            break  # undecodable == torn: replay stops at the last good
        obj["_walltime"] = ts
        yield obj
        off += _REC.size + n


class _Series:
    """One series' bounded point ring. Appends must be monotone in
    ts — a non-monotone sample is DROPPED (counted), because history
    with rewinds cannot answer range queries truthfully."""

    __slots__ = ("points",)

    def __init__(self, max_points: int):
        self.points: deque = deque(maxlen=max_points)

    def append(self, ts: float, value: float) -> bool:
        if self.points and ts <= self.points[-1][0]:
            return False
        self.points.append((ts, value))
        return True


class TSDB:
    """The store. All public methods are thread-safe (the collector's
    reader threads append while HTTP query threads read)."""

    def __init__(self, root: Optional[str] = None, *,
                 retention_secs: float = DEFAULT_RETENTION_SECS,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_points: int = DEFAULT_MAX_POINTS,
                 max_series: int = DEFAULT_MAX_SERIES,
                 resume: bool = False):
        self.root = root
        self.retention_secs = float(retention_secs)
        self.max_bytes = int(max_bytes)
        self.segment_bytes = int(segment_bytes)
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        self._by_source: Dict[str, Dict[str, _Series]] = {}
        #: Per-source bounded annotation ring: alert transitions and
        #: span digests shipped in sample-frame meta.
        self._events: Dict[str, deque] = {}
        self._file = None
        self._file_bytes = 0
        self._samples_total = _reg.counter(
            "gol_tpu_tsdb_samples_total",
            "Samples accepted into the history store",
        )
        self._dropped = {
            reason: _reg.counter(
                "gol_tpu_tsdb_dropped_samples_total",
                "Samples the history store refused",
                {"reason": reason},
            ) for reason in ("non_monotone", "cardinality")
        }
        self._torn = _reg.counter(
            "gol_tpu_tsdb_torn_records_total",
            "Records dropped at a torn segment tail on resume",
        )
        self._series_gauge = _reg.gauge(
            "gol_tpu_tsdb_series", "Live series across all sources",
        )
        self._bytes_gauge = _reg.gauge(
            "gol_tpu_tsdb_bytes", "On-disk bytes across history segments",
        )
        if root:
            os.makedirs(root, exist_ok=True)
            if resume:
                self._replay()
            # Always a FRESH segment: the previous one may end in a
            # torn tail, and appending past a tear would corrupt it.
            self._roll()

    # -- ingest ------------------------------------------------------

    def append(self, source: str, ts: float, samples, *,
               meta: Optional[dict] = None, log_record: bool = True,
               walltime: Optional[float] = None) -> int:
        """Apply one decoded sample batch; returns accepted count."""
        accepted = []
        with self._lock:
            series = self._by_source.setdefault(source, {})
            for key, value in samples:
                s = series.get(key)
                if s is None:
                    if len(series) >= self.max_series:
                        self._dropped["cardinality"].inc()
                        continue
                    s = series[key] = _Series(self.max_points)
                if s.append(ts, value):
                    accepted.append([key, value])
                else:
                    self._dropped["non_monotone"].inc()
            if meta:
                self._note_meta(source, ts, meta)
            if accepted:
                self._samples_total.inc(len(accepted))
                self._series_gauge.set(
                    sum(len(m) for m in self._by_source.values())
                )
                if log_record and self._file is not None:
                    self._log_locked(
                        {"t": "s", "src": source, "ts": ts,
                         "s": accepted},
                        walltime=walltime,
                    )
        return len(accepted)

    def _note_meta(self, source: str, ts: float, meta: dict) -> None:
        ring = self._events.setdefault(source, deque(maxlen=256))
        for tr in meta.get("alerts") or []:
            if isinstance(tr, dict):
                ring.append({"ts": ts, "kind": "alert", **{
                    k: tr.get(k) for k in ("rule", "from", "to")
                }})
        spans = meta.get("spans")
        if isinstance(spans, dict):
            ring.append({"ts": ts, "kind": "spans", **spans})

    # -- persistence (recorder discipline) ---------------------------

    def _log_locked(self, obj: dict,
                    walltime: Optional[float] = None) -> None:
        payload = _pack(obj)
        if self._file_bytes + _REC.size + len(payload) \
                > self.segment_bytes:
            self._roll_locked()
        try:
            self._file.write(
                _REC.pack(len(payload),
                          time.time() if walltime is None else walltime)
                + payload
            )
            self._file.flush()
        except OSError:
            log.exception("history segment append failed")
            return
        self._file_bytes += _REC.size + len(payload)

    def _roll(self) -> None:
        with self._lock:
            self._roll_locked()

    def _roll_locked(self) -> None:
        if not self.root:
            return
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
        millis = int(time.time() * 1000)
        # A same-millisecond roll (tests) must not reuse a filename.
        segs = scan_segments(self.root)
        if segs and millis <= segs[-1][0]:
            millis = segs[-1][0] + 1
        path = os.path.join(self.root, f"hist-{millis:016d}.tlog")
        self._file = open(path, "ab")
        self._file_bytes = 0
        # Keyframe first: the segment is self-interpretable even after
        # every older one is evicted.
        state: Dict[str, Dict[str, list]] = {}
        for src, series in self._by_source.items():
            last = {k: list(s.points[-1]) for k, s in series.items()
                    if s.points}
            if last:
                state[src] = last
        payload = _pack({"t": "key", "state": state})
        try:
            self._file.write(
                _REC.pack(len(payload), millis / 1000.0) + payload
            )
            self._file.flush()
            self._file_bytes = _REC.size + len(payload)
        except OSError:
            log.exception("history keyframe write failed")
        self._evict_locked()

    def _evict_locked(self) -> None:
        segs = scan_segments(self.root)
        total = 0
        sizes = []
        for _, path in segs:
            try:
                n = os.path.getsize(path)
            except OSError:
                n = 0
            sizes.append(n)
            total += n
        cutoff = (time.time() - 1.5 * self.retention_secs) * 1000
        # Oldest first; never the newest (active) segment.
        for (millis, path), n in zip(segs[:-1], sizes[:-1]):
            if total <= self.max_bytes and millis >= cutoff:
                break
            try:
                os.remove(path)
                total -= n
            except OSError:
                pass
        self._bytes_gauge.set(total)

    def _replay(self) -> None:
        """Resume: replay every surviving segment into memory, seeded
        by keyframes (a keyframe's values re-append behind the monotone
        guard, so duplicates across a segment boundary self-dedup)."""
        for _, path in scan_segments(self.root):
            for obj in read_records(path):
                kind = obj.get("t")
                try:
                    if kind == "key":
                        for src, series in (obj.get("state")
                                            or {}).items():
                            for key, (ts, value) in series.items():
                                self.append(src, float(ts),
                                            [(key, float(value))],
                                            log_record=False)
                    elif kind == "s":
                        self.append(
                            str(obj["src"]), float(obj["ts"]),
                            [(k, float(v)) for k, v in obj["s"]],
                            log_record=False,
                        )
                except (KeyError, TypeError, ValueError):
                    self._torn.inc()
                    break

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    # -- reads -------------------------------------------------------

    def sources(self) -> List[str]:
        with self._lock:
            return sorted(self._by_source)

    def events(self, source: str) -> List[dict]:
        with self._lock:
            return list(self._events.get(source) or ())

    def _copy_points(self, source: Optional[str],
                     name: str) -> List[List[Tuple[float, float]]]:
        """Point lists of every series named `name` (exact metric name,
        labels ignored) in `source` (all sources when None)."""
        out = []
        with self._lock:
            srcs = ([source] if source is not None
                    else list(self._by_source))
            for src in srcs:
                for key, s in (self._by_source.get(src) or {}).items():
                    if key == name or key.startswith(name + "{"):
                        out.append(list(s.points))
        return out

    def _bucket_series(self, source: Optional[str], family: str):
        """[(le_bound, points)] for every `<family>_bucket` series."""
        out = []
        with self._lock:
            srcs = ([source] if source is not None
                    else list(self._by_source))
            for src in srcs:
                for key, s in (self._by_source.get(src) or {}).items():
                    if not key.startswith(family + "_bucket{"):
                        continue
                    m = re.search(r'le="([^"]*)"', key)
                    if not m:
                        continue
                    try:
                        bound = float(m.group(1))
                    except ValueError:
                        continue
                    out.append((bound, list(s.points)))
        return out

    def latest(self, source: str,
               max_age: Optional[float] = None,
               now: Optional[float] = None) -> Dict[str, float]:
        """Last value per series of one source (a Series dict the
        scrape-layer helpers consume directly)."""
        now = time.time() if now is None else now
        out = {}
        with self._lock:
            for key, s in (self._by_source.get(source) or {}).items():
                if not s.points:
                    continue
                ts, value = s.points[-1]
                if max_age is not None and now - ts > max_age:
                    continue
                out[key] = value
        return out

    def at(self, source: str, t: float,
           lookback: Optional[float] = None) -> Dict[str, float]:
        """Series dict of one source as of time `t` (last sample at or
        before it, within `lookback`)."""
        out = {}
        with self._lock:
            for key, s in (self._by_source.get(source) or {}).items():
                v = _value_at(list(s.points), t, lookback)
                if v is not None:
                    out[key] = v
        return out

    def last_sample_time(self, source: Optional[str] = None
                         ) -> Optional[float]:
        with self._lock:
            srcs = ([source] if source is not None
                    else list(self._by_source))
            latest = None
            for src in srcs:
                for s in (self._by_source.get(src) or {}).values():
                    if s.points:
                        ts = s.points[-1][0]
                        if latest is None or ts > latest:
                            latest = ts
            return latest

    def query(self, expr: str, start: float, end: float, step: float,
              source: Optional[str] = None) -> dict:
        """The /query payload: aggregated across all sources by
        default, or restricted to one. Raises ValueError on a bad
        expr/range (HTTP 400 upstream)."""
        agg, family = parse_expr(expr)
        if not (end > start and step > 0):
            raise ValueError("need end > start and step > 0")
        if (end - start) / step > 100_000:
            raise ValueError("range/step asks for too many points")
        points = eval_expr(self, agg, family, start, end, step,
                           source=source)
        return {
            "expr": expr, "start": start, "end": end, "step": step,
            "series": [{
                "source": source if source is not None else "*",
                "points": [[t, v] for t, v in points],
            }],
        }

    def history_payload(self, since: float,
                        now: Optional[float] = None) -> dict:
        """The /history payload the console's --since mode renders:
        per source, the Series dict at the window's edges plus a
        turns-rate sparkline series."""
        now = time.time() if now is None else now
        start = now - max(1.0, since)
        out = {}
        for src in self.sources():
            cur = self.at(src, now, lookback=since + 30.0)
            if not cur:
                continue
            prev = self.at(src, start, lookback=30.0)
            spark = eval_expr(
                self, "rate", "gol_tpu_engine_turns_total",
                start, now, max(1.0, since / 16), source=src,
            )
            out[src] = {
                "ts": now, "prev_ts": start,
                "series": cur, "prev": prev,
                "spark": [[t, v] for t, v in spark if v is not None],
                "events": self.events(src)[-32:],
            }
        return {"since": since, "now": now, "sources": out}


def _value_at(points: List[Tuple[float, float]], t: float,
              lookback: Optional[float] = None) -> Optional[float]:
    """Last value at or before `t`, no older than `lookback` — the
    staleness horizon Prometheus calls the lookback delta."""
    lo, hi = 0, len(points)
    while lo < hi:
        mid = (lo + hi) // 2
        if points[mid][0] <= t:
            lo = mid + 1
        else:
            hi = mid
    if lo == 0:
        return None
    ts, value = points[lo - 1]
    if lookback is not None and t - ts > lookback:
        return None
    return value


def eval_expr(db: TSDB, agg: str, family: str, start: float,
              end: float, step: float,
              source: Optional[str] = None,
              ) -> List[Tuple[float, Optional[float]]]:
    """Aligned [(t, value|None)] at each step in (start, end]. The
    aggregations mirror the alert evaluator's `_value` semantics, over
    stored history instead of the live instant: sum/max/min/avg
    combine matching series' values-at-t; `rate` is the per-second
    counter increase over the trailing step (reset-guarded, summed
    across series); `delta` the raw difference (gauges); pNN the
    shared bucket-merge quantile of the observations that landed in
    the trailing step."""
    lookback = max(2 * step, 10.0)
    steps = []
    t = start + step
    while t <= end + 1e-9:
        steps.append(t)
        t += step
    if agg in ("p50", "p95", "p99"):
        q = {"p50": 0.5, "p95": 0.95, "p99": 0.99}[agg]
        buckets = db._bucket_series(source, family)
        out = []
        for t in steps:
            per_le: Dict[float, float] = {}
            for bound, points in buckets:
                cur = _value_at(points, t, lookback)
                if cur is None:
                    continue
                prev = _value_at(points, t - step, lookback) or 0.0
                per_le[bound] = per_le.get(bound, 0.0) \
                    + max(0.0, cur - prev)
            if not per_le:
                out.append((t, None))
                continue
            merged = sorted(per_le.items())
            out.append((t, _reg.quantile_from_buckets(merged, q)))
        return out
    series = db._copy_points(source, family)
    out = []
    for t in steps:
        vals = []
        for points in series:
            cur = _value_at(points, t, lookback)
            if cur is None:
                continue
            if agg in ("rate", "delta"):
                prev = _value_at(points, t - step, lookback)
                if prev is None:
                    continue
                d = cur - prev
                if agg == "rate":
                    # Counter reset: the post-reset value is the best
                    # lower bound on the true increase.
                    vals.append(max(0.0, d if d >= 0 else cur) / step)
                else:
                    vals.append(d)
            else:
                vals.append(cur)
        if not vals:
            out.append((t, None))
        elif agg == "max":
            out.append((t, max(vals)))
        elif agg == "min":
            out.append((t, min(vals)))
        elif agg == "avg":
            out.append((t, sum(vals) / len(vals)))
        else:  # sum, rate, delta
            out.append((t, sum(vals)))
    return out
