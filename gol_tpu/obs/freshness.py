"""Freshness plane — end-to-end turn-age SLOs and the alert evaluator.

The whole serving stack exists so an observer's screen tracks the
engine's committed turn, but until this module nothing MEASURED that
contract: metrics counted frames, traces timed hops, and the one
question an operator of a fan-out tree asks — "how far behind the
engine is this leaf, and which hop is eating the lag?" — had no series
and no alarm. Three pieces (docs/OBSERVABILITY.md "Freshness plane"):

- **Turn age.** Every peer-facing server (EngineServer, SessionServer,
  relay downstream, replay server) tracks each peer's last-WRITTEN
  turn against the authoritative committed turn of whatever it serves
  (engine, session, shadow raster, pump position). `TurnClock` keeps a
  bounded (turn, wall-ts) commit history so "peer is at turn T" turns
  into SECONDS: the age is how long ago the first turn the peer is
  missing was committed — a paused engine ages nobody, a degraded
  (frame-shedding) peer ages in real time. Exported per sweep as
  `gol_tpu_server_peer_turn_age_seconds{peer=token}` (a TopKGauge —
  the PR 12 bounded-cardinality rules: top-K worst named, the rest one
  aggregate), an age histogram and a worst-age gauge, both labeled by
  tier. The CLIENT computes the same number for its own applied board
  (`ClientFreshness`, `gol_tpu_client_turn_age_seconds`) on the PR 5
  corrected clock — what a user actually experiences.

- **Hop-stamp hygiene.** Forward-latency math trusts wall-clock stamps
  that cross the wire (`_TAG_FBATCH.ts`, heartbeat turns). `sane_turn`
  / `sane_lag` are the ONE validation both relays and clients apply
  before a stamp reaches a histogram: negative, absurd (1e18),
  non-finite, or bool-typed values are dropped, never observed — a
  hostile stamp cannot corrupt the freshness plane (pinned by the wire
  fuzz suite).

- **Alert evaluator.** A stdlib rules engine running inside the
  metrics sidecar (`obs.http.MetricsServer(alerts=...)`, CLI
  `--alert-rules FILE`): threshold + `for:` duration over
  scraped-or-local series — the rule text evaluates against ANY
  Prometheus text exposition, the local registry's included, so the
  same rule file works against a sidecar's own series and against a
  scrape. `/alerts` serves the JSON state; firing/resolved transitions
  bump counters, note the flight recorder, and surface in
  `obs.console` (ALERT rows, nonzero `--once` exit for CI).

Rule syntax, one rule per line (see parse_rules):

    # name: [agg(]family[)] OP threshold [for DURATION]
    turn_age_p99: p99(gol_tpu_server_turn_age_seconds) > 2 for 30s
    violations:   gol_tpu_invariant_violations_total > 0
    pool_busy:    rate(gol_tpu_writer_pool_busy_seconds_total) > 0.8 for 10s

Pure stdlib (the registry discipline); every hot-path call is host-side
and sweep-granular, never per frame.
"""

from __future__ import annotations

import bisect
import logging
import re
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from gol_tpu import obs
from gol_tpu.obs.registry import quantile_from_buckets

__all__ = [
    "AlertEvaluator",
    "AlertRule",
    "ClientFreshness",
    "ServerFreshness",
    "TurnClock",
    "cumulative_bucket_delta",
    "parse_rules",
    "sane_lag",
    "sane_turn",
]

log = logging.getLogger(__name__)

#: Turn numbers past this are hostile, not deep (the wire's own
#: plausibility ceiling — a u64 header can carry anything).
MAX_TURN = 1 << 62

#: Ages/lags past this are stamp corruption, not staleness: no real
#: serving session is a year behind its engine. Keeps one absurd
#: negative emit stamp from parking a histogram in the +Inf bucket.
MAX_AGE = 366 * 24 * 3600.0


def sane_turn(turn) -> Optional[int]:
    """A wire-carried turn number, validated: int (bools — JSON
    true/false — are hostile here), 0 <= t < MAX_TURN. None otherwise."""
    if isinstance(turn, bool) or not isinstance(turn, int):
        return None
    if not 0 <= turn < MAX_TURN:
        return None
    return turn


def sane_lag(emit_ts, now: Optional[float] = None) -> Optional[float]:
    """Emit-stamp -> lag seconds, made safe to observe: the stamp must
    be a finite number and the resulting lag must land in [0, MAX_AGE)
    (sub-zero readings within clock granularity clamp to 0, exactly
    the PR 5 turn-latency rule; anything further off is a corrupt or
    hostile stamp and returns None — dropped, never observed)."""
    if isinstance(emit_ts, bool) or not isinstance(emit_ts, (int, float)):
        return None
    ts = float(emit_ts)
    if ts != ts or ts in (float("inf"), float("-inf")):
        return None
    lag = (time.time() if now is None else now) - ts
    if lag >= MAX_AGE or lag < -MAX_AGE:
        return None
    return max(0.0, lag)


class TurnClock:
    """Bounded (turn, wall-ts) commit history: the conversion from
    "peer is at turn T" to SECONDS of staleness. `age_of(T)` is how
    long ago the first turn PAST T was committed — 0 when the peer is
    at (or past) the head, and crucially 0 for every peer of a paused
    or settled stream (no commits after T means nothing is missing),
    while a peer falling behind a live stream ages in real time."""

    __slots__ = ("_turns", "_times", "_lock", "capacity")

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._turns: List[int] = []
        self._times: List[float] = []
        self._lock = threading.Lock()

    def note(self, turn, ts: Optional[float] = None) -> None:
        """Record one committed turn (monotone; stale/hostile values
        are dropped — see sane_turn; a non-finite or absurd `ts`,
        e.g. derived from a NaN emit stamp, falls back to now)."""
        t = sane_turn(turn)
        if t is None:
            return
        now = time.time()
        if ts is not None and isinstance(ts, (int, float)) \
                and not isinstance(ts, bool):
            ts = float(ts)
            if ts == ts and abs(now - ts) < MAX_AGE:
                now = ts
        with self._lock:
            if self._turns and t <= self._turns[-1]:
                return
            self._turns.append(t)
            self._times.append(now)
            if len(self._turns) > self.capacity:
                # Drop in blocks: amortized O(1) per note.
                cut = self.capacity // 4
                del self._turns[:cut]
                del self._times[:cut]

    def head(self) -> int:
        with self._lock:
            return self._turns[-1] if self._turns else -1

    def age_of(self, peer_turn: int,
               now: Optional[float] = None) -> float:
        """Seconds since the first commit this peer has NOT seen
        (0 when it is current, or when nothing was ever committed).
        A peer older than the retained history reads the oldest
        retained commit — a lower bound, which is the honest answer."""
        with self._lock:
            if not self._turns or peer_turn >= self._turns[-1]:
                return 0.0
            i = bisect.bisect_right(self._turns, peer_turn)
            ts = self._times[min(i, len(self._times) - 1)]
        age = (time.time() if now is None else now) - ts
        return min(max(0.0, age), MAX_AGE)


#: Labeled children the per-peer age family exposes before collapsing
#: into the {peer="other"} aggregate — the PR 12 cardinality rule.
PEER_AGE_TOPK = 16

#: Minimum seconds between metric-publishing sweeps: sampling rides
#: the heartbeat loops AND the broadcasters' per-chunk housekeeping,
#: and the second caller inside the window is a free no-op.
SAMPLE_MIN_SECS = 0.25


class ServerFreshness:
    """One serving plane's turn-age tracking. The server notes commits
    (`note_commit`) as the authority advances and stamps each peer's
    last-written turn on the connection itself (`_Conn.fresh_turn`, at
    the send sites); `sample()` turns that into the exported series:

    - gol_tpu_server_peer_turn_age_seconds{peer=token}  (TopKGauge)
    - gol_tpu_server_turn_age_seconds{tier=...}         (histogram)
    - gol_tpu_server_worst_turn_age_seconds{tier=...}   (gauge)

    `key` routes multi-authority servers (sessions, recordings): each
    key owns its own TurnClock, so one stalled session cannot age
    another session's watchers."""

    def __init__(self, tier: str):
        self.tier = tier
        self._clocks: Dict[Optional[str], TurnClock] = {}
        self._clock_lock = threading.Lock()
        self._last_sample = 0.0
        #: Peer tokens this instance has published children for —
        #: close() evicts them all, so a shut-down server cannot leave
        #: ghost peers in the shared family.
        self._published: set = set()
        self._peer_ages = obs.registry().topk_gauge(
            "gol_tpu_server_peer_turn_age_seconds",
            "Seconds each attached peer's last-written turn lags the "
            "authoritative committed turn — bounded exposition: top-K "
            "worst labeled, the rest one 'other' aggregate; children "
            "evicted at detach",
            label="peer", cap=PEER_AGE_TOPK,
        )
        self._age_hist = obs.histogram(
            "gol_tpu_server_turn_age_seconds",
            "Peer turn-age distribution (sampled once per liveness "
            "sweep per peer)", {"tier": tier},
        )
        self._worst = obs.gauge(
            "gol_tpu_server_worst_turn_age_seconds",
            "Worst attached peer's turn age at the last sweep "
            "(obs.console's AGE column)", {"tier": tier},
        )

    def clock(self, key: Optional[str] = None) -> TurnClock:
        with self._clock_lock:
            c = self._clocks.get(key)
            if c is None:
                c = self._clocks[key] = TurnClock()
            return c

    def note_commit(self, turn, key: Optional[str] = None,
                    ts: Optional[float] = None) -> None:
        self.clock(key).note(turn, ts)

    def drop_key(self, key: Optional[str]) -> None:
        """Forget a destroyed authority's clock (session destroy)."""
        with self._clock_lock:
            self._clocks.pop(key, None)

    def forget(self, token) -> None:
        """Evict one peer's labeled child at detach (the cardinality
        discipline's teardown half)."""
        self._published.discard(str(token))
        self._peer_ages.remove_child(str(token))

    def close(self) -> None:
        """Server shutdown: evict every child this instance published
        and this tier's gauge/histogram series — a dead server's last
        worst-age reading must not stay glued to the registry (it
        would hold fleet-max AGE columns and `max(...)` alert rules
        hostage forever in any process that serves again)."""
        for token in list(self._published):
            self._peer_ages.remove_child(token)
        self._published.clear()
        obs.registry().remove("gol_tpu_server_worst_turn_age_seconds",
                              {"tier": self.tier})
        obs.registry().remove("gol_tpu_server_turn_age_seconds",
                              {"tier": self.tier})
        with self._clock_lock:
            self._clocks.clear()

    def sample(self, entries: Iterable[Tuple[object, Optional[str]]],
               now: Optional[float] = None, force: bool = False) -> float:
        """One sweep over `(conn, key)` pairs: compute each peer's
        age, publish the per-peer children + histogram + worst gauge.
        Rate-limited (SAMPLE_MIN_SECS) so the broadcaster and the
        heartbeat judge can both call it without double-observing.
        Returns the worst age seen (0.0 on a skipped sweep)."""
        mono = time.monotonic()
        if not force and mono - self._last_sample < SAMPLE_MIN_SECS:
            return 0.0
        self._last_sample = mono
        worst = 0.0
        for conn, key in entries:
            if getattr(conn, "scrub", False):
                # Seek-parked peers are deliberately historical: their
                # staleness is the feature, not an alarm — and any age
                # published BEFORE the park must not stay glued to the
                # top-K family for the park's duration.
                self.forget(conn.token)
                continue
            turn = getattr(conn, "fresh_turn", -1)
            if turn < 0:
                # Never written to (mid-attach, board sync pending):
                # there is no staleness to measure yet — age_of(-1)
                # would read the whole retained history and poison the
                # histogram/worst gauge on every attach.
                continue
            age = self.clock(key).age_of(turn, now)
            worst = max(worst, age)
            token = str(conn.token)
            self._published.add(token)
            self._peer_ages.set_child(token, round(age, 3))
            self._age_hist.observe(age)
        self._worst.set(round(worst, 3))
        return worst


class ClientFreshness:
    """The client-side twin: how stale is THIS process's applied
    board? The head clock advances from everything the server tells us
    about its committed turn — stamped turn events and batch frames
    (emit stamps corrected onto the local clock by the PR 5 offset)
    and heartbeat beacons (which carry the committed turn precisely so
    an idle-attached client still sees progress). `age()` is then the
    TurnClock math against the last APPLIED turn — measured end-to-end
    freshness, the number the canary publishes."""

    def __init__(self):
        self._clock = TurnClock()
        self.applied_turn = -1

    def note_head(self, turn, ts: Optional[float] = None) -> None:
        self._clock.note(turn, ts)

    def note_applied(self, turn) -> None:
        t = sane_turn(turn)
        if t is not None and t > self.applied_turn:
            self.applied_turn = t

    def head(self) -> int:
        return self._clock.head()

    def age(self, now: Optional[float] = None) -> float:
        return self._clock.age_of(self.applied_turn, now)


# --- alert rules ---------------------------------------------------------


_AGGS = ("sum", "max", "min", "avg", "p50", "p95", "p99", "rate")

_RULE_RE = re.compile(
    r"^(?P<name>[A-Za-z_][\w.-]*)\s*:\s*"
    r"(?:(?P<agg>[a-z0-9]+)\s*\(\s*(?P<fam1>[A-Za-z_:][\w:]*)\s*\)"
    r"|(?P<fam2>[A-Za-z_:][\w:]*))\s*"
    r"(?P<op>>=|<=|>|<)\s*"
    r"(?P<thr>-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)"
    r"(?:\s+for\s+(?P<dur>\d+(?:\.\d+)?)(?P<unit>s|m|h)?)?\s*$"
)

_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}

_UNIT_SECS = {None: 1.0, "s": 1.0, "m": 60.0, "h": 3600.0}


def cumulative_bucket_delta(cur: list, prev: Optional[list]) -> list:
    """Window one histogram between two scrapes: cumulative `le`
    buckets at t1 minus the same histogram's buckets at t0 — the
    distribution of observations that arrived IN BETWEEN (the
    histogram_quantile(rate(...)) idea, without a range vector). With
    no previous sample the full histogram is the window. Counts are
    monotone, so the delta is itself a valid cumulative list; an empty
    window (no new observations) yields a zero-total list, which
    quantile_from_buckets maps to None."""
    if not prev:
        return cur

    def prev_at(bound: float) -> int:
        at = 0
        for b, c in prev:
            if b <= bound:
                at = c
            else:
                break
        return at

    return [(b, max(0, c - prev_at(b))) for b, c in cur]


class AlertRule:
    """One parsed rule: `name: agg(family) OP threshold [for dur]`.
    States: ok -> pending (condition true, `for` not yet served) ->
    firing; leaving the condition from firing is a resolve."""

    __slots__ = ("name", "agg", "family", "op", "threshold",
                 "for_secs", "raw", "state", "since", "firing_since",
                 "last_value", "history")

    def __init__(self, name: str, agg: str, family: str, op: str,
                 threshold: float, for_secs: float, raw: str):
        self.name = name
        self.agg = agg
        self.family = family
        self.op = op
        self.threshold = threshold
        self.for_secs = for_secs
        self.raw = raw
        self.state = "ok"
        self.since: Optional[float] = None
        self.firing_since: Optional[float] = None
        self.last_value: Optional[float] = None
        #: (ts, condition) samples — the recorded history `for:` is
        #: judged against (see AlertEvaluator.eval_once /
        #: seed_history). Bounded; pruned to ~2x the for window.
        self.history: deque = deque(maxlen=512)

    def expr(self) -> str:
        base = (self.family if self.agg == "sum"
                else f"{self.agg}({self.family})")
        tail = (f" for {self.for_secs:g}s" if self.for_secs else "")
        return f"{base} {self.op} {self.threshold:g}{tail}"

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "expr": self.expr(),
            "state": self.state,
            "value": self.last_value,
            "threshold": self.threshold,
            "for": self.for_secs,
            "since": self.since,
            "firing_since": self.firing_since,
        }


def parse_rules(text: str) -> List[AlertRule]:
    """Parse a rule file (one rule per line; blanks and `#` comments
    skipped). Raises ValueError naming the offending line — the CLI
    turns that into a STARTUP error, so a typo'd rule file can never
    take the sidecar (or the server behind it) down at runtime."""
    rules: List[AlertRule] = []
    seen = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        m = _RULE_RE.match(line)
        if not m:
            raise ValueError(
                f"alert rule line {lineno}: cannot parse {line!r} "
                "(expected 'name: [agg(]family[)] OP threshold "
                "[for DURATION]')"
            )
        agg = m.group("agg") or "sum"
        if agg not in _AGGS:
            raise ValueError(
                f"alert rule line {lineno}: unknown aggregation "
                f"{agg!r} (one of {', '.join(_AGGS)})"
            )
        name = m.group("name")
        if name in seen:
            raise ValueError(
                f"alert rule line {lineno}: duplicate rule name "
                f"{name!r}"
            )
        seen.add(name)
        family = m.group("fam1") or m.group("fam2")
        for_secs = (float(m.group("dur")) * _UNIT_SECS[m.group("unit")]
                    if m.group("dur") else 0.0)
        rules.append(AlertRule(
            name, agg, family, m.group("op"),
            float(m.group("thr")), for_secs, line,
        ))
    return rules


def load_rules(path: str) -> List[AlertRule]:
    with open(path) as f:
        return parse_rules(f.read())


class AlertEvaluator:
    """Evaluate rules on an interval inside the metrics sidecar.

    The value source is Prometheus TEXT — by default the local
    registry's own exposition, but `eval_once(text=...)` takes any
    scrape, so the identical rule grammar works against a remote
    endpoint (CI harnesses, the fuzz suite). Evaluation can never
    crash the sidecar: a family that does not exist yields None
    (condition false), and any unexpected evaluation error is logged
    and swallowed (pinned by the fuzz suite).

    Transitions are observable three ways: `gol_tpu_alert_firing
    {rule=...}` 0/1 gauges (the console's ALERT rows read these off
    /metrics), `gol_tpu_alert_transitions_total{state=firing|resolved}`
    counters (bench_compare gates `alerts_firing` off a zero
    baseline), and flight-recorder notes — the black box records WHEN
    the SLO broke, next to what the serving plane was doing."""

    def __init__(self, rules: List[AlertRule], *,
                 registry: Optional[object] = None,
                 interval: float = 1.0,
                 series_source=None):
        self.rules = list(rules)
        self._registry = registry if registry is not None \
            else obs.registry()
        #: Optional zero-arg callable returning a Series dict — the
        #: collector points this at its TSDB's merged latest values,
        #: so fleet-wide rules evaluate over COLLECTED series instead
        #: of the collector's own registry.
        self._series_source = series_source
        self.interval = max(0.05, interval)
        self._rate_prev: Dict[str, Tuple[float, float]] = {}
        #: Per-rule previous cumulative buckets: quantile rules are
        #: WINDOWED (observations since the last eval), so one bad
        #: minute cannot latch a p99 rule for the process lifetime.
        self._bucket_prev: Dict[str, list] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._firing_gauge = obs.gauge(
            "gol_tpu_alerts_firing",
            "Alert rules currently in the firing state",
        )
        self._transitions = {
            s: obs.counter(
                "gol_tpu_alert_transitions_total",
                "Alert state transitions", {"state": s},
            ) for s in ("firing", "resolved")
        }
        self._rule_gauges = {
            r.name: obs.gauge(
                "gol_tpu_alert_firing",
                "1 while the named rule fires (obs.console ALERT rows)",
                {"rule": r.name},
            ) for r in self.rules
        }
        for g in self._rule_gauges.values():
            g.set(0)
        self._firing_gauge.set(0)

    # -- lifecycle --

    def start(self) -> "AlertEvaluator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="gol-alerts", daemon=True,
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for r in self.rules:
            obs.registry().remove("gol_tpu_alert_firing",
                                  {"rule": r.name})
        # The aggregate gauge follows the same teardown discipline: a
        # closed evaluator that was firing must not leave the count
        # glued in the registry (a process that serves again would
        # render phantom ALRT columns forever).
        self._firing_gauge.set(0)
        obs.registry().remove("gol_tpu_alerts_firing")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.eval_once()
            except Exception:
                # The evaluator must never take the sidecar down —
                # a broken rule degrades to a logged no-op.
                log.exception("alert evaluation failed")

    # -- evaluation --

    def _value(self, rule: AlertRule, series: dict,
               now: float) -> Optional[float]:
        from gol_tpu.obs.console import (
            histogram_buckets,
            max_series,
            sum_series,
        )

        if rule.agg in ("p50", "p95", "p99"):
            buckets = histogram_buckets(series, rule.family)
            if not buckets:
                return None
            # Windowed, not all-time: the quantile of observations
            # since the LAST eval (cumulative-bucket delta). An
            # all-time quantile over a cumulative histogram would
            # latch — after one bad minute the lifetime p99 stays hot
            # for hours and the rule never resolves.
            prev = self._bucket_prev.get(rule.name)
            self._bucket_prev[rule.name] = buckets
            return quantile_from_buckets(
                cumulative_bucket_delta(buckets, prev),
                {"p50": 0.5, "p95": 0.95, "p99": 0.99}[rule.agg],
            )
        if rule.agg == "rate":
            cur = sum_series(series, rule.family)
            if cur is None:
                return None
            prev = self._rate_prev.get(rule.name)
            self._rate_prev[rule.name] = (now, cur)
            if prev is None or now <= prev[0]:
                return None  # first sample: no rate yet
            return max(0.0, cur - prev[1]) / (now - prev[0])
        if rule.agg == "max":
            return max_series(series, rule.family)
        vals = [v for key, v in series.items()
                if key == rule.family or key.startswith(rule.family + "{")]
        if not vals:
            return None
        if rule.agg == "min":
            return min(vals)
        if rule.agg == "avg":
            return sum(vals) / len(vals)
        return sum(vals)

    def eval_once(self, now: Optional[float] = None,
                  text: Optional[str] = None) -> dict:
        """One evaluation pass over `text` (default: the local
        registry's exposition). Returns the /alerts payload."""
        from gol_tpu.obs import flight
        from gol_tpu.obs.console import parse_prometheus

        now = time.monotonic() if now is None else now
        if text is not None:
            series = parse_prometheus(text)
        elif self._series_source is not None:
            series = self._series_source()
        else:
            series = parse_prometheus(self._registry.prometheus_text())
        with self._lock:
            firing = 0
            for rule in self.rules:
                try:
                    v = self._value(rule, series, now)
                except Exception:
                    log.exception("rule %r evaluation failed", rule.name)
                    v = None
                rule.last_value = v
                cond = v is not None and _OPS[rule.op](v, rule.threshold)
                # `for:` is judged against recorded HISTORY, not just
                # the consecutive-eval clock: the sample log below is
                # what _sustained() reads, and what seed_history()
                # pre-populates from the collector's store after a
                # restart.
                rule.history.append((now, cond))
                horizon = now - max(60.0, 2.0 * rule.for_secs)
                while rule.history and rule.history[0][0] < horizon:
                    rule.history.popleft()
                if cond:
                    if rule.state == "ok":
                        rule.state = "pending"
                        rule.since = now
                    if (rule.state == "pending"
                            and now - rule.since >= rule.for_secs
                            and _sustained(rule, now)):
                        rule.state = "firing"
                        rule.firing_since = now
                        self._transitions["firing"].inc()
                        self._rule_gauges[rule.name].set(1)
                        flight.note("alert.firing", rule=rule.name,
                                    value=v, expr=rule.expr())
                        log.warning("ALERT firing: %s (value %r)",
                                    rule.expr(), v)
                else:
                    if rule.state == "firing":
                        self._transitions["resolved"].inc()
                        self._rule_gauges[rule.name].set(0)
                        flight.note("alert.resolved", rule=rule.name,
                                    value=v, expr=rule.expr())
                        log.warning("alert resolved: %s (value %r)",
                                    rule.expr(), v)
                    rule.state = "ok"
                    rule.since = None
                    rule.firing_since = None
                if rule.state == "firing":
                    firing += 1
            self._firing_gauge.set(firing)
            return self.payload_locked(firing)

    def payload_locked(self, firing: int) -> dict:
        return {
            "rules": [r.as_dict() for r in self.rules],
            "firing": firing,
            "interval": self.interval,
        }

    def payload(self) -> dict:
        """The /alerts endpoint body — sane with zero rules loaded
        (an empty rules list, firing 0), pinned by the fuzz suite."""
        with self._lock:
            firing = sum(1 for r in self.rules if r.state == "firing")
            return self.payload_locked(firing)

    def seed_history(self, values_fn, now: Optional[float] = None
                     ) -> int:
        """Seed each `for:` rule's condition history from STORED
        samples (the collector calls this with its TSDB after
        `--resume`): `values_fn(rule)` returns [(age_seconds, value),
        ...] — ages relative to now, oldest first or not (sorted
        here). A breach that was already N seconds old when this
        evaluator (re)started keeps its pending credit, so a collector
        restart cannot reset every `for:` clock; a recorded good
        sample inside the window keeps blocking the page exactly as a
        live one would. Returns how many rules were seeded pending."""
        now = time.monotonic() if now is None else now
        seeded = 0
        with self._lock:
            for rule in self.rules:
                if not rule.for_secs:
                    continue
                try:
                    samples = values_fn(rule)
                except Exception:
                    log.exception("history seed failed for rule %r",
                                  rule.name)
                    continue
                if not samples:
                    continue
                run_start = None
                for age, v in sorted(samples, key=lambda p: -p[0]):
                    cond = v is not None \
                        and _OPS[rule.op](v, rule.threshold)
                    rule.history.append((now - age, cond))
                    if cond:
                        if run_start is None:
                            run_start = now - age
                    else:
                        run_start = None
                if run_start is not None and rule.state == "ok":
                    rule.state = "pending"
                    rule.since = run_start
                    seeded += 1
        return seeded


def _sustained(rule: AlertRule, now: float) -> bool:
    """True when every recorded condition sample inside the trailing
    `for:` window held — the history-plane firing gate. With live-only
    evaluation this agrees with the pending clock (a false sample
    resets the state machine anyway); with seeded history it is the
    stronger judge: one noisy recorded scrape inside the window blocks
    the page until a clean window accrues."""
    if not rule.for_secs:
        return True
    return all(c for t, c in rule.history if t >= now - rule.for_secs)
