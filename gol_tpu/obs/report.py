"""Session reports — merge per-process traces, render post-mortems.

Two subcommands (stdlib only, no engine import):

  python -m gol_tpu.obs.report merge SERVER.json CLIENT.json -o OUT.json
      Join two (or more) Chrome-trace dumps (`Tracer.dump` / the
      `/trace` endpoint) into ONE Chrome-trace file on the corrected
      timebase: each input's `metadata.clock_offset_seconds` — the
      handshake-estimated offset to the session's reference clock,
      measured by the wire clock probe (docs/OBSERVABILITY.md) — shifts
      its events before the union, so a server-emit span and its
      client-apply span for the same turn (both carry `args.turn`) line
      up on one timeline even across hosts with skewed clocks. Load the
      output in Perfetto / chrome://tracing.

  python -m gol_tpu.obs.report render FLIGHT.json
      Human post-mortem of a flight-recorder dump (`gol_tpu.obs.flight`):
      why/when it dumped, the state it died in, a turn-rate curve from
      the recorded dispatch commits, stall windows, reconnect storms,
      eviction and invariant-violation history, and the biggest metric
      deltas. `render` on a bare path is the default subcommand.

  python -m gol_tpu.obs.report usage LEDGER-DIR [DIR ...]
      Aggregate the accounting plane's crash-safe usage ledgers
      (`gol_tpu.obs.accounting`): every `usage-*.jsonl` segment under
      the given directories — across rollovers, process generations
      and a torn tail from a SIGKILL mid-append — summed into one
      per-principal bill. Intact records all count, corrupt lines are
      skipped, the command never raises on a damaged ledger; `--json`
      emits the machine form, `--sort` picks the ranking resource.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional


# --- merge ---------------------------------------------------------------


def load_trace(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "traceEvents" not in data:
        raise ValueError(f"{path}: not a Chrome-trace dump "
                         "(no traceEvents key)")
    return data


def merge_traces(dumps: list, labels: Optional[list] = None) -> dict:
    """Union the dumps' traceEvents on the corrected timebase. Each
    dump's `metadata.clock_offset_seconds` (offset TO the reference
    clock: ref_time ≈ local_time + offset; None/absent means this dump
    IS the reference, e.g. the server) shifts its events. Distinct pids
    keep the processes apart in the viewer; a process_name metadata
    event labels each."""
    events = []
    offsets = {}
    used_pids = set()
    for i, dump in enumerate(dumps):
        meta = dump.get("metadata") or {}
        off_us = (meta.get("clock_offset_seconds") or 0.0) * 1e6
        pid = orig_pid = meta.get("pid", i)
        # Two containerized processes are routinely both PID 1: a
        # shared pid would interleave both sides into ONE viewer track
        # (with conflicting labels) — remap the later dump instead.
        while pid in used_pids:
            pid = pid * 1000 + i + 1
        used_pids.add(pid)
        label = (labels[i] if labels and i < len(labels) else None) \
            or meta.get("process_label") or f"proc{i}"
        offsets[str(pid)] = {"label": label, "source_pid": orig_pid,
                             "clock_offset_seconds": off_us / 1e6}
        if meta.get("profile_dir"):
            # The device plane's --profile-dir capture: name it next to
            # the merged timeline so the post-mortem links to the full
            # XLA trace.
            offsets[str(pid)]["profile_dir"] = meta["profile_dir"]
        seen_name = False
        for ev in dump.get("traceEvents", []):
            ev = dict(ev)
            if ev.get("ph") == "M":
                seen_name = ev.get("name") == "process_name" or seen_name
            elif "ts" in ev:
                ev["ts"] = ev["ts"] + off_us
            ev["pid"] = pid
            events.append(ev)
        if not seen_name:
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": label}})
    events.sort(key=lambda e: (e.get("ph") == "M" and -1 or 0,
                               e.get("ts", 0)))
    return {
        "traceEvents": events,
        "metadata": {"merged_from": offsets,
                     "timebase": "reference (server) wall clock, "
                                 "clock-probe corrected"},
    }


def hop_legs(merged: dict) -> dict:
    """Per-hop lag attribution over a merged trace (freshness plane,
    docs/OBSERVABILITY.md): every tier marks each turn on the SAME
    root-corrected timebase — `turn.emit` at the root, `turn.forward`
    (with `args.depth`) at each relay hop, `turn.apply` at the leaf
    client — so the end-to-end emit→apply time of a turn decomposes
    EXACTLY into per-hop legs by differencing successive marks. The
    legs sum to the end-to-end number by construction (it is the same
    telescoping difference); clock skew cancels because each dump's
    own measured offset already shifted it onto the root timebase
    (the per-hop PR 5 snap-to-zero rules apply before that offset is
    ever published).

    Returns {"turns": N, "end_to_end_mean_s": ..., "legs": [{"leg":
    label, "mean_s": ..., "max_s": ...}, ...]} over every turn that
    has both an emit and an apply mark (reconnect replays keep the
    earliest mark per stage, like turn_pairs)."""
    stages: dict = {}
    for ev in merged.get("traceEvents", []):
        name = ev.get("name")
        if name not in ("turn.emit", "turn.forward", "turn.apply"):
            continue
        args = ev.get("args") or {}
        turn = args.get("turn")
        if turn is None:
            continue
        ts = ev.get("ts", 0.0)
        slot = stages.setdefault(int(turn), {})
        if name == "turn.forward":
            depth = args.get("depth")
            if depth is None:
                continue
            key = ("fwd", int(depth))
        else:
            key = (name.split(".")[1],)
        if key not in slot or ts < slot[key]:
            slot[key] = ts
    legs: dict = {}
    e2e = []
    for slot in stages.values():
        emit = slot.get(("emit",))
        apply_ts = slot.get(("apply",))
        if emit is None or apply_ts is None or apply_ts < emit:
            continue
        hops = sorted(
            (key[1], ts) for key, ts in slot.items()
            if key[0] == "fwd" and emit <= ts <= apply_ts
        )
        chain = [("emit", emit)] + [
            (f"hop{d}", ts) for d, ts in hops
        ] + [("apply", apply_ts)]
        e2e.append(apply_ts - emit)
        for (a, ta), (b, tb) in zip(chain, chain[1:]):
            legs.setdefault(f"{a}→{b}", []).append(tb - ta)
    return {
        "turns": len(e2e),
        "end_to_end_mean_s": (sum(e2e) / len(e2e) / 1e6) if e2e else None,
        "legs": [
            {"leg": name,
             "mean_s": sum(vals) / len(vals) / 1e6,
             "max_s": max(vals) / 1e6}
            for name, vals in sorted(legs.items())
        ],
    }


def turn_pairs(merged: dict) -> dict:
    """{turn: {"emit": ts_us, "apply": ts_us}} from a merged trace —
    the per-turn wire correlation the acceptance ordering is judged on
    (first emit / first apply per turn; reconnect replays keep the
    earliest)."""
    pairs: dict = {}
    for ev in merged.get("traceEvents", []):
        name = ev.get("name")
        if name not in ("turn.emit", "turn.apply"):
            continue
        turn = (ev.get("args") or {}).get("turn")
        if turn is None:
            continue
        side = "emit" if name == "turn.emit" else "apply"
        slot = pairs.setdefault(int(turn), {})
        ts = ev.get("ts", 0.0)
        if side not in slot or ts < slot[side]:
            slot[side] = ts
    return pairs


def replay_summary(log_dir: str, turn: int,
                   board_out: Optional[str] = None) -> dict:
    """Join the timeline with EXACT board history (gol_tpu.replay,
    docs/REPLAY.md): decode the recording at the nearest state <= turn
    and summarize it — landed turn, alive count, a board digest (the
    bit-identity anchor two post-mortems can compare), optionally the
    raster itself as a PGM. The one numpy-touching corner of this
    otherwise-stdlib module, imported only when --replay-to is asked
    for."""
    import hashlib

    import numpy as np

    from gol_tpu.replay.log import board_at, last_turn

    got = board_at(log_dir, int(turn))
    if got is None:
        return {"requested_turn": int(turn), "error": "no usable "
                f"recording under {log_dir}"}
    landed, board = got
    mask = np.ascontiguousarray((board != 0).astype(np.uint8))
    out = {
        "requested_turn": int(turn),
        "turn": int(landed),
        "recorded_last_turn": int(last_turn(log_dir)),
        "alive": int(np.count_nonzero(mask)),
        "width": int(board.shape[1]),
        "height": int(board.shape[0]),
        "board_sha256": hashlib.sha256(mask.tobytes()).hexdigest(),
        "log_dir": str(log_dir),
    }
    if board_out:
        from gol_tpu.io.pgm import write_pgm

        write_pgm(board_out, board)
        out["board_pgm"] = str(board_out)
    return out


def _cmd_merge(args) -> int:
    dumps = [load_trace(p) for p in args.paths]
    merged = merge_traces(dumps, labels=args.label)
    if args.hops:
        hops = hop_legs(merged)
        merged["metadata"]["hops"] = hops
        if not hops["turns"]:
            print("hops: no turn with both an emit and an apply mark "
                  "(merge a root, its relays and a leaf client)",
                  file=sys.stderr)
        else:
            print(f"hops: {hops['turns']} turns decomposed, "
                  f"end-to-end mean "
                  f"{hops['end_to_end_mean_s'] * 1e3:.2f}ms")
            for leg in hops["legs"]:
                print(f"  {leg['leg']:<16} mean "
                      f"{leg['mean_s'] * 1e3:8.2f}ms   max "
                      f"{leg['max_s'] * 1e3:8.2f}ms")
    if args.replay_to is not None:
        if not args.replay_log:
            print("error: --replay-to needs --replay-log LOG-DIR",
                  file=sys.stderr)
            return 2
        rp = replay_summary(args.replay_log, args.replay_to,
                            board_out=args.replay_board)
        merged["metadata"]["replay"] = rp
        if "error" in rp:
            print(f"replay: {rp['error']}", file=sys.stderr)
        else:
            print(f"replay: turn {rp['turn']} (asked {rp['requested_turn']}"
                  f", recording ends {rp['recorded_last_turn']}), "
                  f"{rp['alive']} alive, board sha256 "
                  f"{rp['board_sha256'][:16]}…"
                  + (f", raster -> {rp['board_pgm']}"
                     if rp.get("board_pgm") else ""))
    out = json.dumps(merged, indent=1)
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
        pairs = turn_pairs(merged)
        matched = sum(1 for v in pairs.values()
                      if "emit" in v and "apply" in v)
        print(f"merged {len(args.paths)} dumps -> {args.output} "
              f"({len(merged['traceEvents'])} events, "
              f"{matched} turns matched emit<->apply)")
        for pid, info in merged["metadata"]["merged_from"].items():
            if info.get("profile_dir"):
                print(f"  {info['label']}: jax profiler capture at "
                      f"{info['profile_dir']}")
    else:
        sys.stdout.write(out + "\n")
    return 0


# --- render --------------------------------------------------------------


def _fmt_ts(ts: Optional[float]) -> str:
    if not ts:
        return "?"
    import datetime

    return datetime.datetime.fromtimestamp(ts).strftime("%H:%M:%S")


def _sparkline(values: list) -> str:
    if not values:
        return ""
    blocks = " ▁▂▃▄▅▆▇█"
    top = max(values) or 1
    return "".join(blocks[min(8, int(v / top * 8))] for v in values)


def render_flight(dump: dict, out=None) -> None:
    """Print the human post-mortem of one flight-recorder payload."""
    out = out or sys.stdout
    w = out.write
    if not dump.get("enabled", True):
        w("flight recorder: DISABLED — %s\n"
          % dump.get("reason", "no reason recorded"))
        return
    w("flight recorder post-mortem\n")
    w("  reason:   %s\n" % (dump.get("reason") or "live snapshot"))
    w("  process:  pid %s%s\n" % (
        dump.get("pid"),
        " (%s)" % dump["process_label"] if dump.get("process_label") else "",
    ))
    w("  dumped:   %s\n" % _fmt_ts(dump.get("dumped_at")))
    off = dump.get("clock_offset_seconds")
    if off is not None:
        w("  clock:    %+.6fs offset to the session reference\n" % off)
    state = dump.get("state")
    if state:
        w("  state:    %s\n" % json.dumps(state, sort_keys=True))

    entries = dump.get("entries", [])
    commits = [e for e in entries if e.get("kind") == "engine.commit"]
    if commits:
        last = commits[-1]
        w("  last committed turn recorded: %s at %s\n"
          % (last.get("turn"), _fmt_ts(last.get("ts"))))
        # Turn-rate curve: turns advanced per wall-second bucket over
        # the recorded window.
        t0, t1 = commits[0]["ts"], commits[-1]["ts"]
        span = max(t1 - t0, 1e-9)
        buckets = min(60, max(1, int(span) + 1))
        rate = [0.0] * buckets
        prev = commits[0].get("turn", 0)
        for e in commits[1:]:
            i = min(buckets - 1, int((e["ts"] - t0) / span * buckets))
            rate[i] += max(0, e.get("turn", prev) - prev)
            prev = e.get("turn", prev)
        w("  turn rate (%.1fs window, %d buckets): |%s|\n"
          % (span, buckets, _sparkline(rate)))
        # Stalls: inter-commit gaps far beyond the typical cadence.
        gaps = [(b["ts"] - a["ts"], a) for a, b in zip(commits, commits[1:])]
        if gaps:
            typical = sorted(g for g, _ in gaps)[len(gaps) // 2]
            thresh = max(1.0, 5.0 * typical)
            stalls = [(g, a) for g, a in gaps if g > thresh]
            if stalls:
                w("  stalls (> %.2fs between dispatch commits):\n" % thresh)
                for g, a in stalls[:10]:
                    w("    %.2fs after turn %s (%s)\n"
                      % (g, a.get("turn"), _fmt_ts(a.get("ts"))))
            else:
                w("  stalls: none (max gap %.3fs)\n"
                  % max(g for g, _ in gaps))

    by_kind: dict = {}
    for e in entries:
        by_kind.setdefault(e.get("kind"), []).append(e)
    lifecycle = [k for k in by_kind
                 if k and not k.startswith("engine.commit")]
    if lifecycle:
        w("  lifecycle events:\n")
        for k in sorted(lifecycle):
            evs = by_kind[k]
            w("    %-28s x%-4d last %s\n"
              % (k, len(evs), _fmt_ts(evs[-1].get("ts"))))
    storms = [e["ts"] for e in entries
              if e.get("kind") in ("client.reconnected", "server.evict")]
    # A storm is a RATE, not a lifetime count: three benign reconnects
    # hours apart (nightly restarts) must not cry wolf. Flag >= 3
    # events inside any sliding 5-minute window.
    STORM_N, STORM_WINDOW = 3, 300.0
    worst = None
    for i in range(len(storms) - STORM_N + 1):
        span_s = storms[i + STORM_N - 1] - storms[i]
        if span_s <= STORM_WINDOW and (worst is None or span_s < worst):
            worst = span_s
    if worst is not None:
        w("  RECONNECT STORM: %d+ reconnect/eviction events within "
          "%.1fs\n" % (STORM_N, worst))
    violations = [e for e in entries
                  if e.get("kind") == "invariant.violation"]
    if violations:
        w("  INVARIANT VIOLATIONS: %d (latest: %s)\n"
          % (len(violations), violations[-1]))

    deltas = dump.get("metric_deltas") or {}
    moved = sorted(
        ((k, v) for k, v in deltas.items()
         if isinstance(v, (int, float)) and v),
        key=lambda kv: -abs(kv[1]),
    )
    if moved:
        w("  top metric deltas since armed:\n")
        for k, v in moved[:12]:
            w("    %-58s %+g\n" % (k, v))
    if dump.get("dropped"):
        w("  (%d older notes evicted from the ring)\n" % dump["dropped"])


def _cmd_render(args) -> int:
    with open(args.path) as f:
        dump = json.load(f)
    render_flight(dump)
    return 0


# --- usage ---------------------------------------------------------------


def _cmd_usage(args) -> int:
    """Offline twin of the console's TOP-by-cost view, fed by ledger
    segments instead of live sidecars — the bill survives every crash
    the processes did."""
    from gol_tpu.obs.accounting import RESOURCES, read_ledger

    totals: dict = {}
    for d in args.dirs:
        for p, res in read_ledger(d).items():
            dst = totals.setdefault(p, {})
            for k, v in res.items():
                dst[k] = dst.get(k, 0.0) + v
    if args.as_json:
        print(json.dumps({"principals": totals, "sort": args.sort},
                         indent=1, sort_keys=True))
        return 0
    ranked = sorted(totals,
                    key=lambda p: (-totals[p].get(args.sort, 0.0), p))
    print(f"usage ledger — {len(ranked)} principals over "
          f"{len(args.dirs)} dir(s), sorted by {args.sort}")
    hdr = f"{'PRINCIPAL':<21}  " + "  ".join(
        f"{r:>19}" for r in RESOURCES
    )
    print(hdr)
    rows = list(ranked) + ["TOTAL"]
    grand = {r: sum(t.get(r, 0.0) for t in totals.values())
             for r in RESOURCES}
    for p in rows:
        res = grand if p == "TOTAL" else totals[p]
        cells = "  ".join(f"{res.get(r, 0.0):>19.6g}" for r in RESOURCES)
        print(f"{p[:21]:<21}  {cells}")
    return 0


# --- entry ---------------------------------------------------------------


def main(argv: Optional[list] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Bare-path convenience: `report FLIGHT.json` renders it.
    if argv and argv[0] not in ("merge", "render", "usage",
                                "-h", "--help"):
        argv.insert(0, "render")
    ap = argparse.ArgumentParser(
        prog="python -m gol_tpu.obs.report",
        description="Merge per-process trace dumps / render "
                    "flight-recorder post-mortems",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="join trace dumps onto one "
                                      "clock-corrected timeline")
    mp.add_argument("paths", nargs="+",
                    help="Chrome-trace dumps (server first is "
                         "conventional; offsets come from each dump's "
                         "own metadata)")
    mp.add_argument("-o", "--output", default=None,
                    help="write the merged trace here (default stdout)")
    mp.add_argument("-l", "--label", action="append", default=None,
                    metavar="NAME",
                    help="override process labels, in input order "
                         "(repeatable — useful when merging N relays "
                         "that all call themselves 'connect')")
    mp.add_argument("--hops", action="store_true",
                    help="per-hop lag attribution (freshness plane): "
                         "decompose each turn's emit→apply time into "
                         "per-hop legs from the merged turn.emit / "
                         "turn.forward / turn.apply marks — the legs "
                         "sum to the end-to-end number exactly; the "
                         "table prints and the breakdown lands in "
                         "metadata.hops")
    mp.add_argument("--replay-to", type=int, default=None,
                    dest="replay_to", metavar="TURN",
                    help="time-travel debugging (gol_tpu.replay): "
                         "decode the --replay-log recording at TURN "
                         "and join the exact board state (landed "
                         "turn, alive count, sha256 digest) into the "
                         "merged metadata")
    mp.add_argument("--replay-log", default=None, dest="replay_log",
                    metavar="LOG-DIR",
                    help="the recording to decode for --replay-to (a "
                         "session's replay/ directory)")
    mp.add_argument("--replay-board", default=None, dest="replay_board",
                    metavar="OUT.pgm",
                    help="with --replay-to: also write the decoded "
                         "raster as a PGM snapshot")
    mp.set_defaults(fn=_cmd_merge)
    rp = sub.add_parser("render", help="human post-mortem of a "
                                       "flight-recorder dump")
    rp.add_argument("path")
    rp.set_defaults(fn=_cmd_render)
    up = sub.add_parser("usage", help="aggregate crash-safe usage "
                                      "ledger segments into one "
                                      "per-principal bill")
    up.add_argument("dirs", nargs="+", metavar="LEDGER-DIR",
                    help="directories holding usage-*.jsonl segments "
                         "(the CLI writes <out>/usage/)")
    up.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable totals instead of the table")
    up.add_argument("--sort", default="flops",
                    choices=("flops", "dispatch_seconds", "host_seconds",
                             "wire_bytes", "queue_frame_seconds",
                             "turns"),
                    help="resource the table ranks on (default flops)")
    up.set_defaults(fn=_cmd_usage)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
