"""gol_tpu.obs — unified observability: metrics, spans, black box,
device plane, fleet console.

Five planes (catalog: docs/OBSERVABILITY.md):

- **metrics** — Counter / Gauge / Histogram in a process-global
  Registry (`gol_tpu.obs.registry`), exposed as Prometheus text and
  JSON, served live by `MetricsServer` (`gol_tpu.obs.http`, CLI
  `--metrics-port`);
- **spans** — the named-span tracer (`gol_tpu.obs.tracing`): every hop
  of a session (engine dispatch, stepper entry, wire frames, client
  apply, lifecycle) records into a bounded ring exported as
  Chrome-trace JSON (`/trace`); `python -m gol_tpu.obs.report merge`
  joins server + client dumps onto one clock-corrected timeline;
- **black box** — the flight recorder (`gol_tpu.obs.flight`): a
  crash-surviving ring of recent lifecycle notes + metric deltas,
  dumped crash-atomically on SIGTERM / fatal engine errors / peer
  eviction / reconnect exhaustion, live at `/flightrecorder`, rendered
  by `python -m gol_tpu.obs.report render`;
- **device plane** (`gol_tpu.obs.device`): BELOW the jit boundary —
  compile watcher with cause attribution, cost_analysis FLOPs/bytes,
  memory census + HBM watermark, the `fits()` capacity estimator, the
  per-dispatch device-vs-host time split, `--profile-dir`;
- **fleet console** (`gol_tpu.obs.console`): ABOVE the process —
  `python -m gol_tpu.obs.console`, a top-like live view over N
  `/metrics` endpoints with merged fleet percentiles.

Instrumented layers and their series (catalog: docs/OBSERVABILITY.md):

- engine dispatch cadence/chunking   engine/distributor.py  gol_tpu_engine_*
- stepper dispatch + halo traffic    parallel/stepper.py    gol_tpu_stepper_*, gol_tpu_halo_*
- server accept/broadcast/queues     distributed/server.py  gol_tpu_server_*
- client decode/apply + turn latency distributed/client.py  gol_tpu_client_*
- invariant violations               analysis/invariants.py gol_tpu_invariant_violations_total

Ground rules (enforced by the `obs-in-jit` linter check): metrics,
spans and flight notes are host-side and dispatch/event-granular —
never inside a jit/pallas trace, never per cell. `GOL_TPU_METRICS=0`
(or `set_enabled(False)`) turns all three planes off behind a single
flag check — zero wrappers built, no ring allocations.

Stdlib-only on purpose: `analysis.invariants` must stay importable from
worker processes and the linter CLI with zero dependency cost, and it
counts its violations here.
"""

from gol_tpu.obs.registry import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    TopKGauge,
    atomic_write_text,
    counter,
    enabled,
    evict_entity,
    exponential_buckets,
    gauge,
    histogram,
    merge_cumulative_buckets,
    quantile_from_buckets,
    registry,
    remove,
    set_enabled,
    track_entity_series,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsServer",
    "REGISTRY",
    "Registry",
    "TopKGauge",
    "atomic_write_text",
    "counter",
    "enabled",
    "evict_entity",
    "exponential_buckets",
    "gauge",
    "histogram",
    "merge_cumulative_buckets",
    "quantile_from_buckets",
    "registry",
    "remove",
    "set_enabled",
    "track_entity_series",
]


def __getattr__(name):
    # MetricsServer lazily, so importing gol_tpu.obs from invariants /
    # worker processes never pulls http.server machinery it won't use.
    if name == "MetricsServer":
        from gol_tpu.obs.http import MetricsServer

        return MetricsServer
    raise AttributeError(name)
