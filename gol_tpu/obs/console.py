"""Fleet console — `top` for a gol_tpu fleet, over N `/metrics` sidecars.

The obs planes below one process are rich (metrics, spans, the black
box), but a multi-tenant server plus N clients/relays had no aggregated
view at all: an operator tailed N curl loops. This module is the plane
ABOVE the process:

    python -m gol_tpu.obs.console 127.0.0.1:9100 127.0.0.1:9101
    python -m gol_tpu.obs.console 9100 --once          # CI snapshot
    python -m gol_tpu.obs.console 9100 --json --once   # machine form

Each endpoint is one process's `--metrics-port` sidecar. The console
scrapes `/metrics` (Prometheus text — parsed by `gol_tpu.obs.scrape`,
the layer shared with the controller; stdlib only) on an interval and
renders one row per endpoint: committed turn, turns/s (rate between
scrapes), live sessions/peers, worst peer lag, shed/degradation
counters, clock offset, compile count, the HBM/live-buffer watermark,
and p50/p95/p99 turn latency computed from the histogram buckets via
the registry's own `quantile_from_buckets` (one quantile
implementation for every surface). A `TOTAL` row sums the fleet,
merging the latency histograms across endpoints before taking
percentiles (`merge_cumulative_buckets`) — fleet percentiles are NOT
averages of per-endpoint percentiles.

Each scrape also fetches the sidecar's `/usage` payload (accounting
plane, PR 17): the per-endpoint payloads join into ONE fleet
TOP-by-cost table — a row per principal summed across tiers, ranked
on `--sort-usage`, a BUDG column for soft-budget state, a TOTAL row
equal to the summed per-process grand totals, and `--principal ID`
drills one tenant down to which endpoint billed what. Sidecars that
predate the plane (404) or opted out (`GOL_TPU_ACCOUNTING=0`) simply
contribute no usage rows.

A controller sidecar (control plane, PR 18) renders as a `ctl`-tagged
row plus a desired-vs-observed diff line under the tree — the console
is where an operator checks whether the reconciler has converged.

`--once` prints a single non-interactive snapshot (no rates — there is
no previous sample) and exits 0 as long as every endpoint answered —
the CI mode `scripts/metrics_smoke.sh` drives. Live mode redraws with
ANSI clears every `--interval` seconds until Ctrl-C. A down endpoint
renders as `DOWN` and never kills the loop (fleets have partial
outages; that is when you want the console most).

Stdlib only, read-only, loopback-friendly: every request carries a
timeout, nothing is written anywhere.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
from collections import deque
from typing import List, Optional

# The scrape + join layer moved to gol_tpu.obs.scrape (PR 18) so the
# controller reconciles against the SAME parser and tree the console
# renders. Re-exported here: every pre-18 `from gol_tpu.obs.console
# import parse_prometheus` call site (tests, smoke harnesses) keeps
# working.
from gol_tpu.obs.scrape import (  # noqa: F401  (re-exports)
    Endpoint,
    Series,
    build_tree,
    fleet_snapshot,
    histogram_buckets,
    history_snapshot,
    label_value,
    max_series,
    merge_usage,
    parse_prometheus,
    sum_series,
)

__all__ = [
    "Endpoint",
    "build_tree",
    "fleet_snapshot",
    "histogram_buckets",
    "history_snapshot",
    "label_value",
    "main",
    "merge_usage",
    "parse_prometheus",
    "render",
    "render_tree",
    "render_usage",
    "spark",
    "sum_series",
]


# --- rendering -----------------------------------------------------------


def _num(v, unit: str = "") -> str:
    if v is None:
        return "-"
    if unit == "bytes":
        for suffix, scale in (("G", 1 << 30), ("M", 1 << 20),
                              ("K", 1 << 10)):
            if v >= scale:
                return f"{v / scale:.1f}{suffix}"
        return str(int(v))
    if unit == "s":
        return f"{v * 1e3:.1f}ms" if abs(v) < 1.0 else f"{v:.2f}s"
    if abs(v) >= 1e6:
        return f"{v / 1e6:.2f}M"
    if abs(v) >= 1e4:
        return f"{v / 1e3:.1f}k"
    if v == int(v):
        return str(int(v))
    return f"{v:.1f}"


#: Sparkline glyphs, lowest to highest.
_SPARK_BARS = "▁▂▃▄▅▆▇█"


def spark(points, width: int = 8) -> str:
    """Unicode sparkline of a [[ts, value], ...] (or bare value) list
    — the per-row turns/s history column. Min-max normalized; a flat
    non-empty series renders mid-height so 'steady' and 'no data'
    ('-') look different."""
    vals = [(p[1] if isinstance(p, (list, tuple)) else p)
            for p in (points or [])]
    vals = [v for v in vals if v is not None][-width:]
    if not vals:
        return "-"
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_BARS[3] * len(vals)
    n = len(_SPARK_BARS) - 1
    return "".join(
        _SPARK_BARS[round((v - lo) / (hi - lo) * n)] for v in vals
    )


_COLUMNS = (
    ("endpoint", "ENDPOINT", 21, None),
    ("turn", "TURN", 9, ""),
    ("turns_per_sec", "TURNS/S", 9, ""),
    ("spark", "HIST", 8, None),
    ("sessions", "SESS", 5, ""),
    ("peers", "PEERS", 5, ""),
    ("peer_lag", "LAG", 5, ""),
    ("turn_age_s", "AGE", 8, "s"),
    ("alerts_firing", "ALRT", 4, ""),
    ("degradations", "DEGR", 5, ""),
    ("reconnects", "RECON", 5, ""),
    ("clock_offset_s", "CLOCK", 8, "s"),
    ("compiles", "COMPS", 5, ""),
    ("hbm_watermark_bytes", "HBM^", 7, "bytes"),
    ("p50", "P50", 8, "s"),
    ("p95", "P95", 8, "s"),
    ("p99", "P99", 8, "s"),
)


def _cells(row: dict) -> list:
    lat = row.get("latency") or {}
    cells = []
    for key, _, width, unit in _COLUMNS:
        if key == "endpoint":
            name = str(row.get("endpoint", "TOTAL"))
            if row.get("mode") == "replay":
                # Replay servers render DISTINCTLY: no engine behind
                # them, their SESS column carries recordings.
                name = f"{name} ⟲"
            elif row.get("controller") is not None:
                name = f"{name} ctl"
            cells.append(name[:width])
        elif key == "sessions" and row.get("mode") == "replay":
            cells.append(_num(row.get("recordings"), unit))
        elif key == "spark":
            cells.append(spark(row.get("spark"))[:width])
        elif key in ("p50", "p95", "p99"):
            cells.append(_num(lat.get(key), "s"))
        else:
            cells.append(_num(row.get(key), unit))
    return cells


def render_tree(tree: List[dict], out=None) -> None:
    out = out or sys.stdout

    def line(n, indent):
        peers = n.get("peers")
        ws = n.get("ws_peers")
        bits = [f"{_num(peers)} peers" if peers is not None else "?"]
        if ws:
            bits.append(f"{_num(ws)} ws")
        if n.get("hop_latency_s") is not None and n.get("upstream"):
            bits.append(f"+{_num(n['hop_latency_s'], 's')}/hop")
        tag = ("replay" if n.get("mode") == "replay"
               else "root" if not n.get("upstream")
               else f"depth {_num(n.get('depth'))}")
        out.write(f"{'  ' * indent}{'└─ ' if indent else ''}"
                  f"{n['listen']}  [{tag}]  {', '.join(bits)}\n")
        for c in n["children"]:
            line(c, indent + 1)

    if tree:
        out.write("fan-out tree:\n")
        for n in tree:
            line(n, 0)


def render_controller(rows: List[dict], out=None) -> None:
    """The desired-vs-observed diff line per controller row: whether
    the reconciler has converged, and how many actions it has taken
    (error outcomes called out — they are the off-zero bench gate)."""
    out = out or sys.stdout
    for r in rows:
        if not r.get("up") or r.get("controller") is None:
            continue
        want, have = r.get("desired_nodes"), r.get("observed_nodes")
        if want is None and have is None:
            continue
        state = ("converged" if want == have
                 else f"RECONCILING ({_num(have)}/{_num(want)} nodes)")
        bits = [f"desired {_num(want)}", f"observed {_num(have)}", state]
        acts = r.get("controller_actions")
        if acts is not None:
            bits.append(f"{_num(acts)} actions")
        fails = r.get("controller_action_failures")
        if fails:
            bits.append(f"!! {_num(fails)} failed")
        out.write(f"controller {r.get('controller')} "
                  f"@{r['endpoint']}:  {', '.join(bits)}\n")


#: TOP-by-cost columns: (resource key, header, width, unit).
_USAGE_COLUMNS = (
    ("flops", "FLOPS", 9, ""),
    ("dispatch_seconds", "DISP", 8, "s"),
    ("host_seconds", "HOST", 8, "s"),
    ("wire_bytes", "WIRE", 7, "bytes"),
    ("queue_frame_seconds", "QOCC", 8, "s"),
    ("turns", "TURNS", 9, ""),
)


def render_usage(usage: Optional[dict], out=None, top: int = 10,
                 principal: Optional[str] = None,
                 rows: Optional[List[dict]] = None) -> None:
    """The fleet TOP-by-cost table: one row per principal (session id,
    peer:<token>, or the anonymous `legacy` tier), most expensive
    first on the snapshot's sort key, a BUDG column for soft-budget
    state (OVER is advisory — the accounting plane never enforces),
    and a TOTAL row summing the per-process grand totals. With
    `principal` set, a drill-down follows: that tenant's share at each
    scraped endpoint (which tier billed what)."""
    out = out or sys.stdout
    w = out.write
    if usage is None:
        return
    by = usage["by_principal"]
    ranked = usage["ranked"]
    w(f"usage — top by {usage.get('sort', 'flops')} "
      f"({len(ranked)} principals)\n")
    header = f"{'PRINCIPAL':<21}  " + "  ".join(
        f"{title:>{width}}" for _, title, width, _ in _USAGE_COLUMNS
    ) + "  BUDG"
    w(header + "\n")

    def line(name, res):
        cells = "  ".join(
            f"{_num(res.get(key), unit):>{width}}"
            for key, _, width, unit in _USAGE_COLUMNS
        )
        budg = "OVER" if res.get("over_budget") else "-"
        w(f"{name[:21]:<21}  {cells}  {budg:>4}\n")

    for p in ranked[:max(0, top)]:
        line(p, by[p])
    if len(ranked) > top:
        w(f"… {len(ranked) - top} more principals\n")
    line("TOTAL", usage.get("total") or {})
    if principal is not None:
        w(f"usage drill-down — {principal}:\n")
        found = False
        for r in rows or []:
            u = r.get("usage") or {}
            res = (u.get("principals") or {}).get(principal)
            if res is None:
                continue
            found = True
            line(f"  @{r.get('endpoint', '?')}", res)
        if not found:
            w("  (no endpoint reports this principal)\n")


def render(snap: dict, out=None, clear: bool = False,
           usage_top: int = 10,
           principal: Optional[str] = None) -> None:
    out = out or sys.stdout
    w = out.write
    if clear:
        w("\x1b[2J\x1b[H")
    w("gol_tpu fleet console — %s  (%d/%d endpoints up)\n" % (
        time.strftime("%H:%M:%S"),
        snap["total"]["up"], snap["total"]["endpoints"],
    ))
    header = "  ".join(
        f"{title:>{width}}" if key != "endpoint" else f"{title:<{width}}"
        for key, title, width, _ in _COLUMNS
    )
    w(header + "\n")
    for row in snap["rows"]:
        if not row.get("up"):
            w(f"{row['endpoint']:<21}  DOWN  {row.get('error', '')}\n")
            continue
        cells = _cells(row)
        w("  ".join(
            f"{c:>{width}}" if key != "endpoint" else f"{c:<{width}}"
            for (key, _, width, _), c in zip(_COLUMNS, cells)
        ) + "\n")
    if len(snap["rows"]) > 1:
        t = dict(snap["total"])
        t["endpoint"] = "TOTAL"
        cells = _cells(t)
        w("  ".join(
            f"{c:>{width}}" if key != "endpoint" else f"{c:<{width}}"
            for (key, _, width, _), c in zip(_COLUMNS, cells)
        ) + "\n")
    tree = snap.get("tree") or []
    if any(n["children"] or n.get("upstream") for n in tree):
        render_tree(tree, out)
    render_controller(snap["rows"], out)
    render_usage(snap.get("usage"), out, top=usage_top,
                 principal=principal, rows=snap["rows"])
    for a in snap["total"].get("alerts") or []:
        w(f"!! ALERT firing on {a['endpoint']}: {a['rule']}\n")
    viol = snap["total"].get("violations")
    if viol:
        w(f"!! INVARIANT VIOLATIONS across the fleet: {int(viol)}\n")


# --- entry ---------------------------------------------------------------


def _duration_secs(spec: str) -> float:
    """'60s' / '5m' / '1h' / bare '90' -> seconds."""
    m = re.fullmatch(r"(\d+(?:\.\d+)?)\s*([smh]?)", spec.strip())
    if not m:
        raise ValueError(f"cannot parse duration {spec!r} "
                         "(expected e.g. 60s, 5m, 1h)")
    return float(m.group(1)) * {"": 1.0, "s": 1.0,
                                "m": 60.0, "h": 3600.0}[m.group(2)]


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gol_tpu.obs.console",
        description="top-like live view over gol_tpu /metrics endpoints",
    )
    ap.add_argument("endpoints", nargs="+", metavar="HOST:PORT",
                    help="metrics sidecars to scrape (a bare PORT means "
                         "loopback; full http:// URLs accepted)")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (CI mode; exits 1 "
                         "if any endpoint is down, 2 if any alert rule "
                         "is firing)")
    ap.add_argument("--interval", type=float, default=2.0, metavar="SEC",
                    help="live-mode refresh cadence (default 2)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the snapshot as JSON instead of the table")
    ap.add_argument("--sort-usage", default="flops",
                    choices=("flops", "dispatch_seconds", "host_seconds",
                             "wire_bytes", "queue_frame_seconds",
                             "turns"),
                    help="resource the TOP-by-cost usage table ranks on "
                         "(default flops)")
    ap.add_argument("--usage-top", type=int, default=10, metavar="N",
                    help="labeled rows in the usage table before the "
                         "'… more' fold (default 10)")
    ap.add_argument("--principal", default=None, metavar="ID",
                    help="drill into one tenant: its usage share at "
                         "every scraped endpoint")
    ap.add_argument("--since", default=None, metavar="DUR",
                    help="render from the history plane instead of "
                         "live scrapes: the single endpoint is a "
                         "--collector sidecar, rows come from its "
                         "/history window of DUR (e.g. 60s, 5m)")
    args = ap.parse_args(argv)

    if args.since is not None:
        try:
            since = _duration_secs(args.since)
        except ValueError as e:
            ap.error(str(e))
        if len(args.endpoints) != 1:
            ap.error("--since takes exactly one endpoint "
                     "(the collector's metrics sidecar)")

        def take_snapshot():
            return history_snapshot(args.endpoints[0], since,
                                    usage_sort=args.sort_usage)
    else:
        eps = [Endpoint(spec) for spec in args.endpoints]
        #: Live-mode per-endpoint turns/s history feeding the HIST
        #: sparkline column (the --since path gets its points from
        #: the collector instead).
        spark_hist: dict = {}

        def take_snapshot():
            snap = fleet_snapshot(eps, usage_sort=args.sort_usage)
            for row in snap["rows"]:
                if not row.get("up"):
                    continue
                ring = spark_hist.setdefault(
                    row["endpoint"], deque(maxlen=16))
                if row.get("turns_per_sec") is not None:
                    ring.append(row["turns_per_sec"])
                row["spark"] = list(ring)
            return snap

    if args.once:
        snap = take_snapshot()
        if args.as_json:
            snap = {**snap, "rows": [
                {k: v for k, v in r.items() if k != "latency_buckets"}
                for r in snap["rows"]
            ]}
            print(json.dumps(snap, indent=1))
        else:
            render(snap, usage_top=args.usage_top,
                   principal=args.principal)
        if snap["down"]:
            return 1
        # Firing alerts are a CI failure too (freshness plane): the
        # distinct code lets a harness tell "endpoint down" from
        # "SLO broken".
        return 2 if snap["total"].get("alerts") else 0
    try:
        while True:
            snap = take_snapshot()
            if args.as_json:
                print(json.dumps(snap["total"]))
            else:
                render(snap, clear=True, usage_top=args.usage_top,
                       principal=args.principal)
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
