"""Fleet scraping — the Prometheus-text parser + topology join that
both read-paths of the fleet share.

Until the control plane (PR 18) there was exactly one consumer of the
`/metrics` sidecars: the console (`gol_tpu.obs.console`), and the
parser, the per-endpoint row builder, and the relay-tree join lived
inside it. The controller (`gol_tpu.control`) must observe the SAME
fleet through the SAME join — re-implementing the exposition parser in
a second place is how two views of one fleet drift apart. So the whole
scrape layer lives here, and the console imports it:

- `parse_prometheus` — text exposition -> {name{labels}: value},
- `sum_series` / `max_series` / `label_value` / `histogram_buckets`
  — family readers over that dict,
- `Endpoint` — one `/metrics` sidecar, scraped into the row dict the
  console renders and the controller reconciles against (keeps the
  previous sample for rates, fetches `/usage` and `/alerts` context),
- `build_tree` — the relay fan-out forest joined from `listen` /
  `upstream` labels alone,
- `merge_usage` / `fleet_snapshot` — the fleet-level aggregation.

Stdlib only, read-only, every request timeboxed — the scrape layer
must be safe to point at a half-dead fleet, because that is exactly
when both of its consumers matter most.
"""

from __future__ import annotations

import json
import re
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from gol_tpu.obs.registry import (
    merge_cumulative_buckets,
    quantile_from_buckets,
)

__all__ = [
    "Endpoint",
    "Series",
    "build_tree",
    "fleet_snapshot",
    "histogram_buckets",
    "label_value",
    "max_series",
    "merge_usage",
    "parse_prometheus",
    "sum_series",
]

_SCRAPE_TIMEOUT = 5.0

#: name{labels} -> value. Histogram buckets stay individual series
#: (`<name>_bucket{...,le="x"}`) — `histogram_buckets` reassembles.
Series = Dict[str, float]

_LINE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$'
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Series:
    """The text exposition format -> {name{labels}: float}. Comments
    and malformed lines are skipped (a scraper must survive whatever a
    half-written exposition throws at it); label order is preserved as
    emitted (the registry emits sorted labels, so keys are stable)."""
    out: Series = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if not m:
            continue
        name, labels, raw = m.group(1), m.group(2) or "", m.group(3)
        try:
            v = float(raw.replace("+Inf", "inf").replace("-Inf", "-inf"))
        except ValueError:
            continue
        out[name + labels] = v
    return out


def _labels_of(key: str) -> Dict[str, str]:
    i = key.find("{")
    if i < 0:
        return {}
    return {m.group(1): m.group(2).replace('\\"', '"')
            for m in _LABEL.finditer(key[i:])}


def _name_of(key: str) -> str:
    i = key.find("{")
    return key if i < 0 else key[:i]


def sum_series(metrics: Series, name: str,
               match: Optional[Dict[str, str]] = None) -> Optional[float]:
    """Sum every series of one family (optionally filtered by label
    values); None when absent — callers render '-' for metrics a
    process legitimately doesn't export (a client has no sessions)."""
    total, seen = 0.0, False
    for key, v in metrics.items():
        if _name_of(key) != name:
            continue
        if match:
            labels = _labels_of(key)
            if any(labels.get(k) != want for k, want in match.items()):
                continue
        total += v
        seen = True
    return total if seen else None


def max_series(metrics: Series, name: str) -> Optional[float]:
    vals = [v for key, v in metrics.items() if _name_of(key) == name]
    return max(vals) if vals else None


def label_value(metrics: Series, name: str,
                label: str) -> Optional[str]:
    """The `label` value of the first series of one family — for
    info-style gauges (`gol_tpu_relay_node_info{listen,upstream}`,
    `gol_tpu_server_listen_addr{addr}`) whose labels ARE the data."""
    for key in metrics:
        if _name_of(key) == name:
            v = _labels_of(key).get(label)
            if v is not None:
                return v
    return None


def histogram_buckets(metrics: Series, name: str) -> list:
    """Reassemble `<name>_bucket{...,le=...}` series into the
    cumulative [(bound, cum)] form `quantile_from_buckets` takes,
    merging across any non-`le` label sets (one population per
    endpoint)."""
    by_labels: Dict[Tuple, list] = {}
    for key, v in metrics.items():
        if _name_of(key) != f"{name}_bucket":
            continue
        labels = _labels_of(key)
        le = labels.pop("le", None)
        if le is None:
            continue
        bound = float("inf") if le == "+Inf" else float(le)
        by_labels.setdefault(tuple(sorted(labels.items())), []).append(
            (bound, int(v))
        )
    lists = [sorted(buckets) for buckets in by_labels.values()]
    return merge_cumulative_buckets(lists)


class Endpoint:
    """One scraped `/metrics` sidecar, with the previous sample kept so
    rates (turns/s) come from successive scrapes."""

    def __init__(self, spec: str):
        self.spec = spec
        base = spec if "://" in spec else f"http://{spec}"
        if re.fullmatch(r"\d+", spec):
            base = f"http://127.0.0.1:{spec}"
        base = base.rstrip("/")
        if base.endswith("/metrics"):
            # The CLI banner prints the full .../metrics URL — pasting
            # it verbatim must work, not 404 on /metrics/metrics.
            base = base[: -len("/metrics")]
        self.base = base
        self.url = base + "/metrics"
        self.prev: Optional[Tuple[float, Series]] = None
        self.last_error: Optional[str] = None

    def scrape(self) -> Optional[dict]:
        """One sample -> the row dict `render` consumes, or None when
        the endpoint is down (`last_error` says why)."""
        try:
            with urllib.request.urlopen(
                self.url, timeout=_SCRAPE_TIMEOUT
            ) as resp:
                text = resp.read().decode("utf-8", "replace")
        except Exception as e:
            self.last_error = repr(e)
            return None
        self.last_error = None
        now = time.monotonic()
        metrics = parse_prometheus(text)
        row = self._row(metrics, now)
        row["usage"] = self._fetch_usage()
        self.prev = (now, metrics)
        return row

    def _fetch_usage(self) -> Optional[dict]:
        """The sidecar's `/usage` payload (accounting plane), or None
        — a pre-accounting sidecar 404s and an opted-out process
        answers `{"enabled": false}`; both degrade to 'no usage
        columns', never to a DOWN row (the endpoint's /metrics already
        answered)."""
        try:
            with urllib.request.urlopen(
                self.base + "/usage", timeout=_SCRAPE_TIMEOUT
            ) as resp:
                payload = json.loads(resp.read().decode("utf-8",
                                                        "replace"))
        except Exception:
            return None
        if not isinstance(payload, dict) or not payload.get("enabled"):
            return None
        return payload

    def _turns(self, metrics: Series) -> Optional[float]:
        parts = [sum_series(metrics, "gol_tpu_engine_turns_total"),
                 sum_series(metrics, "gol_tpu_session_turns_total"),
                 # Replay servers have no engine: their turn flow is
                 # the pump position (gol_tpu.replay), so rate math
                 # works unchanged on replay rows.
                 sum_series(metrics, "gol_tpu_replay_turns_total")]
        vals = [p for p in parts if p is not None]
        return sum(vals) if vals else None

    def _row(self, metrics: Series, now: float) -> dict:
        turns = self._turns(metrics)
        recordings = sum_series(metrics, "gol_tpu_replay_recordings")
        rate = None
        if self.prev is not None and turns is not None:
            t0, prev_metrics = self.prev
            prev_turns = self._turns(prev_metrics)
            if prev_turns is not None and now > t0:
                rate = max(0.0, (turns - prev_turns) / (now - t0))
        lat = histogram_buckets(
            metrics, "gol_tpu_client_turn_latency_seconds"
        )
        rtt = sum_series(metrics, "gol_tpu_relay_upstream_rtt_seconds")
        # Freshness plane: the worst turn age this endpoint reports —
        # a server's worst-peer sweep gauge, a client/canary's own
        # applied-turn age, whichever is present and worst.
        ages = [v for v in (
            max_series(metrics, "gol_tpu_server_worst_turn_age_seconds"),
            max_series(metrics, "gol_tpu_client_turn_age_seconds"),
        ) if v is not None]
        firing = [
            _labels_of(key)["rule"]
            for key, v in metrics.items()
            if _name_of(key) == "gol_tpu_alert_firing" and v >= 1
            and "rule" in _labels_of(key)
        ]
        # The firing COUNT: the evaluator's gauge when present (0
        # renders as 0 — "no alerts" differs from "no evaluator"),
        # else derived from the per-rule gauges.
        alerts_firing = sum_series(metrics, "gol_tpu_alerts_firing")
        if alerts_firing is None and firing:
            alerts_firing = float(len(firing))
        return {
            # Topology identity (the relay tier's sidecar labels): how
            # the fan-out tree is joined from scrapes alone.
            "listen": (
                label_value(metrics, "gol_tpu_relay_node_info",
                            "listen")
                or label_value(metrics, "gol_tpu_server_listen_addr",
                               "addr")
            ),
            "upstream": label_value(metrics, "gol_tpu_relay_node_info",
                                    "upstream"),
            "depth": max_series(metrics, "gol_tpu_relay_depth"),
            "relay_peers": sum_series(metrics, "gol_tpu_relay_peers"),
            "ws_peers": sum_series(metrics, "gol_tpu_relay_ws_peers"),
            "hop_latency_s": None if rtt is None else rtt / 2.0,
            "hop_clock_offset_s": sum_series(
                metrics, "gol_tpu_relay_clock_offset_seconds"
            ),
            "endpoint": self.spec,
            "up": True,
            # Replay servers (gol_tpu.replay): no engine series at all
            # — they export listen_addr + the replay family, and the
            # row renders from those instead of as a broken '-' row.
            # Keyed on recordings > 0, not presence: a live session
            # server that merely ANSWERED a seek verb registers the
            # family at 0 (import side effect) and must keep its
            # engine row.
            "mode": "replay" if recordings else None,
            "recordings": recordings,
            "replay_serves": sum_series(
                metrics, "gol_tpu_replay_serves_total"
            ),
            "turn": (
                max_series(metrics, "gol_tpu_replay_position_turn")
                if recordings
                else max_series(metrics, "gol_tpu_engine_committed_turn")
            ),
            "turns_total": turns,
            "turns_per_sec": rate,
            "sessions": sum_series(metrics, "gol_tpu_sessions_active"),
            "peers": sum_series(metrics, "gol_tpu_server_peers"),
            "peer_lag": max_series(metrics,
                                   "gol_tpu_server_peer_lag_frames"),
            "turn_age_s": max(ages) if ages else None,
            "alerts_firing": alerts_firing,
            "alerts": sorted(firing),
            "degradations": sum_series(
                metrics, "gol_tpu_server_degradations_total"
            ),
            "shed": sum_series(metrics,
                               "gol_tpu_server_shed_frames_total"),
            "reconnects": sum_series(
                metrics, "gol_tpu_client_reconnects_total"
            ),
            "clock_offset_s": sum_series(
                metrics, "gol_tpu_client_clock_offset_seconds"
            ),
            "compiles": sum_series(metrics,
                                   "gol_tpu_device_compiles_total"),
            "hbm_watermark_bytes": max_series(
                metrics, "gol_tpu_device_hbm_watermark_bytes"
            ),
            "violations": sum_series(
                metrics, "gol_tpu_invariant_violations_total"
            ),
            # Control plane (PR 18): a controller's sidecar exports its
            # identity + desired-vs-observed node counts; every other
            # process leaves these None and the console skips the row
            # decoration.
            "controller": label_value(
                metrics, "gol_tpu_controller_info", "spec"
            ),
            "desired_nodes": sum_series(
                metrics, "gol_tpu_controller_desired_nodes"
            ),
            "observed_nodes": sum_series(
                metrics, "gol_tpu_controller_observed_nodes"
            ),
            "controller_actions": sum_series(
                metrics, "gol_tpu_controller_actions_total"
            ),
            "controller_action_failures": sum_series(
                metrics, "gol_tpu_controller_actions_total",
                {"outcome": "error"},
            ),
            # Writer-pool saturation (broadcast tier): the controller's
            # scale rule reads busy-seconds off the root's sidecar.
            "writer_busy_s": sum_series(
                metrics, "gol_tpu_server_writer_pool_busy_seconds_total"
            ),
            "latency_buckets": lat,
            "latency": {
                q: quantile_from_buckets(lat, p)
                for q, p in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))
            } if lat else None,
        }


def build_tree(rows: List[dict]) -> List[dict]:
    """Join scraped endpoints into the fan-out topology: a relay's
    `upstream` label matches its parent's `listen` label (roots export
    `gol_tpu_server_listen_addr`, relays `gol_tpu_relay_node_info`).
    Returns the forest of root nodes — each node carries depth, peer
    counts (TCP + WS) and the per-hop added latency (half the hop's
    min clock-probe RTT). Endpoints whose upstream is not scraped
    become roots of their own subtree (partial scrapes stay useful);
    an accidental relay cycle cannot recurse (visited set)."""
    by_listen = {r["listen"]: r for r in rows
                 if r.get("up") and r.get("listen")}
    children: Dict[str, List[dict]] = {}
    roots = []
    for r in by_listen.values():
        up = r.get("upstream")
        if up and up in by_listen and up != r["listen"]:
            children.setdefault(up, []).append(r)
        else:
            roots.append(r)
    visited = set()

    def node(r) -> dict:
        visited.add(r["listen"])
        kids = [c for c in sorted(children.get(r["listen"], []),
                                  key=lambda x: x["listen"])
                if c["listen"] not in visited]
        return {
            "endpoint": r["endpoint"],
            "listen": r["listen"],
            "upstream": r.get("upstream"),
            "mode": r.get("mode"),
            "depth": r.get("depth"),
            "peers": (r.get("relay_peers")
                      if r.get("upstream") is not None
                      else r.get("peers")),
            "ws_peers": r.get("ws_peers"),
            "hop_latency_s": r.get("hop_latency_s"),
            "hop_clock_offset_s": r.get("hop_clock_offset_s"),
            "children": [node(c) for c in kids],
        }

    forest = [node(r) for r in
              sorted(roots, key=lambda x: x["listen"])]
    # Pure cycles (A -> B -> A) have no root at all: promote their
    # members so every scraped node appears exactly once.
    for r in sorted(by_listen.values(), key=lambda x: x["listen"]):
        if r["listen"] not in visited:
            forest.append(node(r))
    return forest


def merge_usage(rows: List[dict],
                sort_key: str = "flops") -> Optional[dict]:
    """Join every endpoint's `/usage` payload into the fleet view:
    per-principal resource sums across processes (a tenant served by
    a session server AND billed wire bytes by a relay is ONE row),
    ranked most-expensive-first on `sort_key`, plus a fleet TOTAL
    equal to the sum of the per-process `totals` blocks (which include
    already-forgotten principals — the fleet bill survives eviction).
    None when no scraped endpoint exposes the accounting plane."""
    by: Dict[str, dict] = {}
    total: Dict[str, float] = {}
    budgets: Dict[str, float] = {}
    seen = False
    for r in rows:
        u = r.get("usage")
        if not u:
            continue
        seen = True
        for p, res in (u.get("principals") or {}).items():
            dst = by.setdefault(p, {"over_budget": False})
            for k, v in res.items():
                if k == "over_budget":
                    dst["over_budget"] = bool(dst["over_budget"] or v)
                else:
                    dst[k] = dst.get(k, 0.0) + float(v)
        for k, v in (u.get("totals") or {}).items():
            total[k] = total.get(k, 0.0) + float(v)
        for k, v in (u.get("budgets") or {}).items():
            if v is not None:
                budgets[k] = v
    if not seen:
        return None
    ranked = sorted(by, key=lambda p: (-by[p].get(sort_key, 0.0), p))
    return {"by_principal": by, "ranked": ranked, "total": total,
            "budgets": budgets, "sort": sort_key}


def fleet_snapshot(endpoints: List[Endpoint],
                   usage_sort: str = "flops") -> dict:
    """Scrape every endpoint once; returns {"rows": [...], "total":
    {...}, "down": [spec, ...], "tree": [...], "usage": {...}|None} —
    `tree` is the relay fan-out forest (build_tree), `usage` the
    fleet-joined TOP-by-cost view (merge_usage). The TOTAL row merges
    latency histograms across endpoints BEFORE taking percentiles."""
    # Concurrent scrapes: one black-holed endpoint (a hanging TCP
    # connect eats its whole 5s timeout) must not freeze the healthy
    # rows' refresh — a partial outage is when the console matters.
    from concurrent.futures import ThreadPoolExecutor

    rows, down = [], []
    with ThreadPoolExecutor(max_workers=min(16, len(endpoints))) as pool:
        scraped = list(pool.map(lambda ep: ep.scrape(), endpoints))
    for ep, row in zip(endpoints, scraped):
        if row is None:
            down.append(ep.spec)
            rows.append({"endpoint": ep.spec, "up": False,
                         "error": ep.last_error})
        else:
            rows.append(row)
    return snapshot_from_rows(rows, down, len(endpoints), usage_sort)


def snapshot_from_rows(rows: List[dict], down: List[str],
                       n_endpoints: int,
                       usage_sort: str = "flops") -> dict:
    """Join already-built rows into the snapshot shape (`fleet_snapshot`
    after its scrapes; `history_snapshot` from collector queries)."""
    live = [r for r in rows if r.get("up")]

    def total_of(key):
        vals = [r[key] for r in live if r.get(key) is not None]
        return sum(vals) if vals else None

    merged_lat = merge_cumulative_buckets(
        [r["latency_buckets"] for r in live if r.get("latency_buckets")]
    )
    ages = [r["turn_age_s"] for r in live
            if r.get("turn_age_s") is not None]
    alerts = [{"endpoint": r["endpoint"], "rule": rule}
              for r in live for rule in (r.get("alerts") or [])]
    total = {
        "endpoints": n_endpoints,
        "up": len(live),
        "turns_per_sec": total_of("turns_per_sec"),
        "sessions": total_of("sessions"),
        "peers": total_of("peers"),
        "turn_age_s": max(ages) if ages else None,
        "alerts_firing": total_of("alerts_firing"),
        "alerts": alerts,
        "degradations": total_of("degradations"),
        "compiles": total_of("compiles"),
        "violations": total_of("violations"),
        "latency": {
            q: quantile_from_buckets(merged_lat, p)
            for q, p in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))
        } if merged_lat else None,
    }
    return {"rows": rows, "total": total, "down": down,
            "tree": build_tree(rows),
            "usage": merge_usage(live, usage_sort)}


def history_snapshot(collector: str, since: float,
                     usage_sort: str = "flops") -> dict:
    """The console's `--since` snapshot: rows rendered from a
    collector's `/history` window payload instead of live scrapes.
    One row per remote-writing source; the row builder is the SAME
    `Endpoint._row` the live path uses (series dict in, row out), fed
    the window-edge series the store returns — rates therefore come
    from history, not from successive scrapes. The collector being
    down is the one DOWN row (there is nothing else to ask)."""
    spec = collector if "://" in collector else f"http://{collector}"
    if re.fullmatch(r"\d+", collector):
        spec = f"http://127.0.0.1:{collector}"
    url = (f"{spec.rstrip('/')}/history?"
           f"since={float(since):g}")
    try:
        with urllib.request.urlopen(url, timeout=_SCRAPE_TIMEOUT) as r:
            payload = json.loads(r.read().decode("utf-8", "replace"))
    except Exception as e:
        return snapshot_from_rows(
            [{"endpoint": collector, "up": False, "error": repr(e)}],
            [collector], 1, usage_sort,
        )
    rows = []
    for src in sorted(payload.get("sources") or {}):
        h = payload["sources"][src]
        ep = Endpoint(src)
        prev = h.get("prev")
        if prev:
            ep.prev = (float(h.get("prev_ts") or 0.0), prev)
        row = ep._row(h.get("series") or {}, float(h.get("ts") or 0.0))
        row["endpoint"] = src
        row["spark"] = h.get("spark") or []
        row["events"] = h.get("events") or []
        row["usage"] = None
        rows.append(row)
    snap = snapshot_from_rows(rows, [], len(rows), usage_sort)
    snap["since"] = payload.get("since", since)
    snap["collector"] = collector
    return snap
