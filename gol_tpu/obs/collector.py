"""Remote-write telemetry over the framed wire: the history plane.

Two halves:

- `RemoteWriter` rides inside EVERY metrics sidecar (`--remote-write
  HOST:PORT`): a daemon thread samples the process registry each
  interval and pushes the series that changed since the last
  acknowledged state as one `_TAG_MSAMPLES` frame (absolute values —
  the delta encoding is in the series *set*), plus a periodic full
  snapshot on the keyframe cadence and after every reconnect. The link
  follows the client discipline the distributed plane already lives
  by: connect/send deadlines, jittered exponential backoff, reconnect.
  A slow or dead collector SHEDS samples (counted on
  `gol_tpu_remote_write_shed_samples_total`) — it can never wedge the
  serving process, because nothing outside this thread ever blocks on
  the link.

- `CollectorServer` is the `--collector [HOST:]PORT` process's ingest:
  an accept loop, one reader thread per link, JSON-only hellos before
  anything binary is parsed (the engine server's pre-auth rule), every
  malformed frame surfacing as WireError that closes THAT link and
  nothing else. Accepted sample batches land in the TSDB (bounded
  rings + crash-atomic segment logs) and keep serving `/query` no
  matter what a peer throws at the socket.

Alert state transitions and span digests ride in the frame's meta
dict; the collector stores them as per-source annotations.
"""

from __future__ import annotations

import hmac
import importlib
import logging
import random
import socket
import threading
import time
from typing import Optional

from gol_tpu.distributed import wire
from gol_tpu.obs.scrape import parse_prometheus
from gol_tpu.obs.tsdb import TSDB

_reg = importlib.import_module("gol_tpu.obs.registry")

__all__ = ["CollectorServer", "RemoteWriter"]

log = logging.getLogger(__name__)

#: Source labels come from the peer's hello — bound and sanitized
#: before they become dict keys, filenames inside keyframes, or label
#: values in the console's history rows.
_SRC_RE = r"^[A-Za-z0-9._:@-]{1,64}$"

_CONNECT_TIMEOUT = 3.0
_IO_TIMEOUT = 5.0
#: A remote writer pushes every ~1 s; a link idle for this long is a
#: dead peer, not a quiet one.
_SERVER_IDLE_TIMEOUT = 60.0
_BACKOFF_CAP = 30.0


class RemoteWriter:
    """Push this process's registry to a collector, shedding on
    failure. Owned by the MetricsServer sidecar (start()/close())."""

    def __init__(self, target: str, *, source: str,
                 interval: float = 1.0,
                 registry: Optional[object] = None,
                 alerts=None, secret: Optional[str] = None,
                 keyframe_every: int = 30):
        host, _, port = target.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.source = source
        self.interval = max(0.05, float(interval))
        self.keyframe_every = max(1, int(keyframe_every))
        self._registry = registry if registry is not None \
            else _reg.registry()
        self._alerts = alerts
        self._secret = secret
        self._sock: Optional[socket.socket] = None
        self._sent: dict = {}
        self._alert_states: dict = {}
        self._pushes_since_full = 0
        self._attempt = 0
        self._retry_at = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pushed = _reg.counter(
            "gol_tpu_remote_write_pushed_samples_total",
            "Samples pushed to the collector",
        )
        self._shed = _reg.counter(
            "gol_tpu_remote_write_shed_samples_total",
            "Samples shed because the collector link was down or slow",
        )
        self._reconnects = _reg.counter(
            "gol_tpu_remote_write_reconnects_total",
            "Collector link (re)connect attempts that succeeded",
        )
        self._errors = _reg.counter(
            "gol_tpu_remote_write_errors_total",
            "Collector link failures (send or connect)",
        )

    # -- lifecycle --

    def start(self) -> "RemoteWriter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="gol-remote-write", daemon=True,
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._close_sock()

    def _close_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- the push loop --

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.push_once()
            except Exception:
                # The writer must never take the sidecar down.
                log.exception("remote-write push failed unexpectedly")

    def _collect(self) -> dict:
        cur = parse_prometheus(self._registry.prometheus_text())
        # Keys past the wire bound would poison whole frames — drop
        # them here (none of our series come close to 512 chars).
        return {k: v for k, v in cur.items()
                if len(k) <= wire.MSAMPLE_KEY_MAX}

    def _meta(self, full: bool) -> Optional[dict]:
        meta = {}
        if self._alerts is not None:
            try:
                transitions = []
                for r in self._alerts.payload().get("rules", []):
                    old = self._alert_states.get(r["name"])
                    if old is not None and old != r["state"]:
                        transitions.append({"rule": r["name"],
                                            "from": old,
                                            "to": r["state"]})
                    self._alert_states[r["name"]] = r["state"]
                if transitions:
                    meta["alerts"] = transitions
            except Exception:
                log.exception("alert transition digest failed")
        if full:
            try:
                from gol_tpu.obs import tracing
                spans = tracing.trace_payload().get("traceEvents", [])
                meta["spans"] = {"events": len(spans)}
            except Exception:
                pass
        return meta or None

    def push_once(self, now: Optional[float] = None) -> bool:
        """One sampling tick. Returns True when the frame went out;
        a down link sheds the changed set and backs off."""
        now = time.time() if now is None else now
        cur = self._collect()
        full = (self._sock is None
                or self._pushes_since_full >= self.keyframe_every)
        changed = (cur if full else {
            k: v for k, v in cur.items() if self._sent.get(k) != v
        })
        meta = self._meta(full)
        if not changed and not meta:
            return True  # nothing new; a quiet tick is not a shed
        if self._sock is None and not self._connect(now):
            self._shed.inc(len(changed))
            return False
        try:
            wire.send_frame(self._sock, wire.samples_to_frame(
                now, sorted(changed.items()), full=full, meta=meta,
            ))
        except (OSError, wire.WireError):
            self._errors.inc()
            self._close_sock()
            self._schedule_retry(now)
            self._shed.inc(len(changed))
            return False
        self._sent = cur
        self._pushes_since_full = 0 if full else \
            self._pushes_since_full + 1
        self._pushed.inc(len(changed))
        self._attempt = 0
        return True

    def _schedule_retry(self, now: float) -> None:
        delay = min(_BACKOFF_CAP, 0.25 * (2 ** min(self._attempt, 8)))
        self._retry_at = now + delay * (0.5 + random.random())
        self._attempt += 1

    def _connect(self, now: float) -> bool:
        if now < self._retry_at:
            return False
        try:
            sock = socket.create_connection(
                self.addr, timeout=_CONNECT_TIMEOUT,
            )
            sock.settimeout(_IO_TIMEOUT)
            hello = {"t": "hello", "mode": "remote-write",
                     "source": self.source, "binary": True}
            if self._secret:
                hello["secret"] = self._secret
            wire.send_msg(sock, hello)
            ack = wire.recv_msg(sock, allow_binary=False)
            if not ack or ack.get("t") != "attach-ack":
                raise wire.WireError(
                    f"collector refused: {ack!r}"
                )
        except (OSError, wire.WireError) as e:
            self._errors.inc()
            self._schedule_retry(now)
            log.debug("collector connect failed: %s", e)
            return False
        self._sock = sock
        self._reconnects.inc()
        # Post-reconnect state is unknown to the collector: force the
        # next frame full so its keyframe chain re-seeds.
        self._pushes_since_full = self.keyframe_every
        return True


class CollectorServer:
    """Accept remote-write links and apply their sample frames to a
    TSDB. Never trusts a peer: JSON-only hello, bounded source labels,
    per-link deadlines, WireError closes one link only."""

    def __init__(self, host: str, port: int, db: TSDB, *,
                 secret: Optional[str] = None):
        import re as _re

        self.db = db
        self._secret = secret
        self._src_re = _re.compile(_SRC_RE)
        self._listener = socket.create_server(
            (host, port), backlog=16, reuse_port=False,
        )
        self.address = self._listener.getsockname()[:2]
        self._lock = threading.Lock()
        self._conns: set = set()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="gol-collector-accept",
            daemon=True,
        )
        self._connections = _reg.gauge(
            "gol_tpu_collector_connections",
            "Live remote-write links",
        )
        self._frames = _reg.counter(
            "gol_tpu_collector_frames_total",
            "Sample frames accepted",
        )
        self._rejected = {
            reason: _reg.counter(
                "gol_tpu_collector_dropped_frames_total",
                "Frames/links the collector refused",
                {"reason": reason},
            ) for reason in ("bad_hello", "auth", "wire", "idle")
        }

    def start(self) -> "CollectorServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout=5)
        self.db.close()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return  # listener closed
            with self._lock:
                self._conns.add(sock)
            threading.Thread(
                target=self._serve_conn, args=(sock, addr),
                name=f"gol-collector-{addr[0]}:{addr[1]}", daemon=True,
            ).start()

    def _hello(self, sock: socket.socket) -> Optional[str]:
        """Validate the pre-auth JSON hello; the peer's source label or
        None (link already answered + closed on refusal)."""
        try:
            msg = wire.recv_msg(sock, allow_binary=False)
        except (OSError, wire.WireError, TimeoutError):
            self._rejected["bad_hello"].inc()
            return None
        if (not isinstance(msg, dict) or msg.get("t") != "hello"
                or msg.get("mode") != "remote-write"
                or not isinstance(msg.get("source"), str)
                or not self._src_re.match(msg["source"])):
            self._rejected["bad_hello"].inc()
            self._refuse(sock, "bad-hello")
            return None
        if self._secret is not None and not hmac.compare_digest(
                str(msg.get("secret") or ""), self._secret):
            self._rejected["auth"].inc()
            self._refuse(sock, "auth")
            return None
        try:
            wire.send_msg(sock, {"t": "attach-ack"})
        except OSError:
            return None
        return msg["source"]

    @staticmethod
    def _refuse(sock: socket.socket, reason: str) -> None:
        try:
            wire.send_msg(sock, {"t": "error", "reason": reason})
        except OSError:
            pass

    def _serve_conn(self, sock: socket.socket, addr) -> None:
        sock.settimeout(_SERVER_IDLE_TIMEOUT)
        self._connections.inc()
        try:
            source = self._hello(sock)
            if source is None:
                return
            while not self._stop.is_set():
                try:
                    msg = wire.recv_msg(sock)
                except TimeoutError:
                    self._rejected["idle"].inc()
                    return
                except (OSError, wire.WireError):
                    # One malformed frame kills one link — the peer
                    # reconnects with a full snapshot; every other
                    # link and the query side keep serving.
                    self._rejected["wire"].inc()
                    return
                if msg is None:
                    return
                if msg.get("t") == "msamples":
                    self._frames.inc()
                    self.db.append(source, msg["ts"], msg["samples"],
                                   meta=msg.get("meta"))
                # hb / unknown kinds: ignorable (forward compat).
        finally:
            self._connections.dec()
            with self._lock:
                self._conns.discard(sock)
            try:
                sock.close()
            except OSError:
                pass
