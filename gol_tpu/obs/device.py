"""Device plane — observability BELOW the jit boundary.

The rest of `gol_tpu.obs` deliberately stops at the dispatch line
(docs/OBSERVABILITY.md's host-side-only rule): metrics, spans and
flight notes record what the HOST did, never what XLA compiled or what
HBM holds. That rule is correct — instrumentation inside a trace would
record once per compile, not per step — but it left the questions the
perf roadmap keeps asking unanswerable from the live endpoints: what
did this run compile and why, what does one dispatch cost in FLOPs and
bytes, how close is the board (or a session bucket) to OOM, and how
much of a dispatch's wall time was device work vs host overhead.

This module answers them WITHOUT breaking the rule: every hook here
fires at a dispatch/compile boundary on the host —

- **compile watcher** (`install_compile_watcher`): a
  `jax.monitoring` duration listener that turns every backend compile
  into a metric + a `device.compile` span + a flight note, attributed
  to the CAUSE the dispatching layer declared via the `cause(...)`
  context manager (bucket growth, a diff-chunk cap change, warm-up —
  the recompile lint's runtime twin: the lint proves shipped code
  cannot recompile per call, the watcher shows what actually compiled
  and what it cost in wall time);
- **cost analysis** (`cost_of` / `publish_cost`): FLOPs / bytes
  accessed / peak temp bytes of a program via
  `lower().compile().cost_analysis()` — an explicit AOT compile, so
  callers opt in at known points (engine startup, bucket creation,
  bench lanes) instead of taxing the hot path;
- **memory census** (`memory_census` / `observe_memory`): live device
  buffer count/bytes (`jax.live_arrays`), per-device allocator stats
  where the backend exposes them (TPU `memory_stats`), and an
  **HBM/live-buffer watermark** gauge — the peak footprint this
  process ever observed;
- **fits()**: a capacity estimator turning the census + the board/
  bucket arithmetic into "will this geometry fit / how many sessions
  can this bucket hold before OOM" answers;
- **dispatch split** (`observe_split`): per-dispatch device-vs-host
  time split histograms, attributed at the block-until-ready
  boundaries the engine already crosses (enqueue = the dispatch call
  returning, sync = the fetched buffers materialising on host, host =
  decode + event fan-out) — no new realizations, no observer tax;
- **profiler driver** (`start_profile` / `stop_profile`): the opt-in
  `--profile-dir` path that wraps `jax.profiler.start_trace` and links
  the capture directory from the trace metadata so `obs.report merge`
  can point a post-mortem at the full XLA capture.

jax imports are lazy (inside functions): importing this module costs
nothing and works in processes that never touch the device. Everything
follows the registry's enablement (`GOL_TPU_METRICS=0` silences the
whole plane).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Optional

import importlib

from gol_tpu import obs
from gol_tpu.obs import flight, tracing

# Live module object — the twin of tracing.py's note (the package
# __init__ shadows the submodule attribute with a function).
_registry = importlib.import_module("gol_tpu.obs.registry")

__all__ = [
    "cause",
    "cost_of",
    "cost_probes_enabled",
    "current_cause",
    "device_budget",
    "enable_cost_probes",
    "fits",
    "install_compile_watcher",
    "max_resident_tiles",
    "memory_census",
    "observe_memory",
    "observe_split",
    "plane_delta",
    "plane_snapshot",
    "publish_cost",
    "start_profile",
    "stop_profile",
    "tile_ext_bytes",
]

#: The jax.monitoring key one backend compile fires exactly once.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: Bounded cause vocabulary — causes are metric LABELS, so free-form
#: strings would unbound the registry. Layers declare one of these via
#: `cause(...)`; anything else lands under its own string at the
#: caller's risk (the shipped callers below use only these).
CAUSE_UNATTRIBUTED = "unattributed"

_cause_stack = threading.local()


@contextlib.contextmanager
def cause(label: str):
    """Declare WHY any compile fired inside this block (thread-local,
    nestable — innermost wins). The compile watcher stamps the label
    onto the metric, the span and the flight note, so a post-mortem
    reads 'bucket-grow recompiled for 1.8s at 14:02' instead of a bare
    compile count."""
    stack = getattr(_cause_stack, "stack", None)
    if stack is None:
        stack = _cause_stack.stack = []
    stack.append(str(label))
    try:
        yield
    finally:
        stack.pop()


def current_cause() -> str:
    stack = getattr(_cause_stack, "stack", None)
    return stack[-1] if stack else CAUSE_UNATTRIBUTED


class _DeviceMetrics:
    """Registry handles, resolved once at import (stdlib-only — the
    registry neither knows nor cares that this plane watches jax)."""

    def __init__(self):
        self.compile_seconds = obs.histogram(
            "gol_tpu_device_compile_seconds",
            "Backend (XLA) compile wall seconds per compilation",
        )
        self._compiles: dict = {}
        phases = ("enqueue", "sync", "host")
        self.split_seconds = {
            p: obs.histogram(
                "gol_tpu_device_dispatch_split_seconds",
                "Per-dispatch wall seconds split at the block-until-"
                "ready boundaries: enqueue (dispatch call returning), "
                "sync (fetched buffers materialising = device work + "
                "transfer), host (decode + event fan-out)",
                {"phase": p},
            ) for p in phases
        }
        self.device_fraction = obs.gauge(
            "gol_tpu_device_fraction",
            "Last fully-split dispatch's sync share of its wall time "
            "(device work + transfer over enqueue+sync+host)",
        )
        self.live_buffers = obs.gauge(
            "gol_tpu_device_live_buffers",
            "Live device arrays at the last census",
        )
        self.live_bytes = obs.gauge(
            "gol_tpu_device_live_bytes",
            "Bytes held by live device arrays at the last census",
        )
        self.watermark = obs.gauge(
            "gol_tpu_device_hbm_watermark_bytes",
            "Peak device-memory footprint this process observed "
            "(allocator bytes_in_use where the backend reports it, "
            "live-array bytes otherwise)",
        )

    def compiles(self, cause_label: str):
        c = self._compiles.get(cause_label)
        if c is None:
            c = self._compiles[cause_label] = obs.counter(
                "gol_tpu_device_compiles_total",
                "Backend (XLA) compilations by declared cause",
                {"cause": cause_label},
            )
        return c


_METRICS = _DeviceMetrics()

_WATCHER_INSTALLED = False


def install_compile_watcher() -> bool:
    """Register the jax.monitoring listener that records every backend
    compile (count by cause, duration histogram, `device.compile` span,
    flight note). Idempotent; returns False where jax.monitoring is
    unavailable. The listener itself is host-side code running at
    compile time — exactly a dispatch boundary, never inside a trace —
    and no-ops behind the registry flag when the plane is disabled."""
    global _WATCHER_INSTALLED
    if _WATCHER_INSTALLED:
        return True
    try:
        import jax.monitoring as mon
    except Exception:
        return False
    mon.register_event_duration_secs_listener(_on_event_duration)
    _WATCHER_INSTALLED = True
    return True


def _on_event_duration(name: str, dur: float, **kw) -> None:
    if name != _COMPILE_EVENT or not _registry._ENABLED:
        return
    why = current_cause()
    _METRICS.compiles(why).inc()
    _METRICS.compile_seconds.observe(dur)
    tracing.add_span("device.compile", "device", time.time() - dur, dur,
                     {"cause": why})
    flight.note("device.compile", cause=why, seconds=round(dur, 4))


# --- cost analysis -------------------------------------------------------

#: Auto cost probes (one small AOT compile per engine/bucket) are a
#: REAL-RUN concern: the CLI enables them so a live `/metrics` carries
#: the cost model, while library embedders and the test suite — which
#: build hundreds of engines and would pay a compile each — default
#: off. Explicit `cost_of`/`publish_cost` calls always work.
_COST_PROBES = False


def enable_cost_probes(on: bool = True) -> None:
    global _COST_PROBES
    _COST_PROBES = bool(on)


def cost_probes_enabled() -> bool:
    return _COST_PROBES and _registry._ENABLED


def cost_of(fn: Callable, *args, **kw) -> dict:
    """FLOPs / bytes of one call of `fn(*args)` from the compiled
    executable's own cost model (`lower().compile().cost_analysis()` +
    `memory_analysis()`). `fn` may be jitted or plain-traceable (a
    plain callable is wrapped in jax.jit; an already-jitted inner fn
    inlines). This performs a REAL ahead-of-time compile — call it at
    known cold points (engine startup, bucket creation, bench lanes),
    never per dispatch. Returns {"error": ...} instead of raising: the
    estimate is advisory and must never kill the run it describes."""
    try:
        import jax

        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        with cause("cost-analysis"):
            compiled = jitted.lower(*args, **kw).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        out = {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        }
        mem = None
        with contextlib.suppress(Exception):
            mem = compiled.memory_analysis()
        if mem is not None:
            out["argument_bytes"] = int(mem.argument_size_in_bytes)
            out["output_bytes"] = int(mem.output_size_in_bytes)
            out["temp_bytes"] = int(mem.temp_size_in_bytes)
            out["generated_code_bytes"] = int(
                mem.generated_code_size_in_bytes
            )
        return out
    except Exception as e:
        return {"error": repr(e)}


def publish_cost(program: str, fn: Callable, *args, **kw) -> dict:
    """`cost_of`, exported: the FLOPs/bytes land as labeled gauges
    (`gol_tpu_device_cost_{flops,bytes_accessed}{program=...}`) so a
    live `/metrics` scrape carries the cost model of the programs this
    process runs, plus a trace event + flight note with the full
    numbers. `program` must come from a BOUNDED vocabulary (shipped:
    "engine.step", "bucket.step") — it is a label."""
    if not _registry._ENABLED:
        return {}
    out = cost_of(fn, *args, **kw)
    if "error" not in out:
        # The accounting plane prices modeled-FLOPs attribution off
        # exactly these published program costs (price x dispatched
        # turns — gol_tpu.obs.accounting).
        from gol_tpu.obs import accounting

        m = accounting.meter()
        if m is not None:
            m.set_price(program, out)
        obs.gauge(
            "gol_tpu_device_cost_flops",
            "cost_analysis FLOPs per call of the named program",
            {"program": program},
        ).set(out["flops"])
        obs.gauge(
            "gol_tpu_device_cost_bytes_accessed",
            "cost_analysis bytes accessed per call of the named program",
            {"program": program},
        ).set(out["bytes_accessed"])
    tracing.event("device.cost", "device", program=program, **{
        k: v for k, v in out.items() if not isinstance(v, str)
    })
    flight.note("device.cost", program=program, **out)
    return out


# --- memory census -------------------------------------------------------

_census_lock = threading.Lock()
_last_census = 0.0
_peak_bytes = 0.0


def memory_census() -> dict:
    """One census of device memory, host-side only: live jax arrays
    (count + summed nbytes), per-device allocator stats where the
    backend reports them (TPU; CPU returns none), and the process-peak
    watermark. Updates the gauges and returns the numbers."""
    global _peak_bytes
    import jax

    arrs = jax.live_arrays()
    live_bytes = 0
    for a in arrs:
        with contextlib.suppress(Exception):
            live_bytes += int(a.nbytes)
    per_device = {}
    in_use = None
    limit = None
    for d in jax.devices():
        ms = None
        with contextlib.suppress(Exception):
            ms = d.memory_stats()
        if ms:
            per_device[str(d)] = {
                k: ms[k] for k in ("bytes_in_use", "peak_bytes_in_use",
                                   "bytes_limit") if k in ms
            }
            in_use = (in_use or 0) + int(ms.get("bytes_in_use", 0))
            if "bytes_limit" in ms:
                limit = (limit or 0) + int(ms["bytes_limit"])
    footprint = in_use if in_use is not None else live_bytes
    with _census_lock:
        _peak_bytes = max(_peak_bytes, float(footprint))
        peak = _peak_bytes
    if _registry._ENABLED:
        _METRICS.live_buffers.set(len(arrs))
        _METRICS.live_bytes.set(live_bytes)
        _METRICS.watermark.set(peak)
    return {
        "live_buffers": len(arrs),
        "live_bytes": live_bytes,
        "bytes_in_use": in_use,
        "bytes_limit": limit,
        "watermark_bytes": peak,
        "per_device": per_device,
    }


def observe_memory(min_interval: float = 0.5) -> None:
    """Rate-limited census for dispatch boundaries: the engine and the
    session manager call this once per committed dispatch; the census
    itself (a live-arrays walk) runs at most every `min_interval`
    seconds, so a 10k-dispatch/s fused run pays one attribute read per
    dispatch and two censuses per second."""
    global _last_census
    if not _registry._ENABLED:
        return
    now = time.monotonic()
    if now - _last_census < min_interval:
        return
    _last_census = now
    with contextlib.suppress(Exception):
        memory_census()


# --- capacity estimation -------------------------------------------------


def device_budget() -> Optional[int]:
    """Device-memory budget in bytes: the GOL_TPU_DEVICE_BUDGET_BYTES
    override when set (explicit operator intent always wins), else the
    allocator's bytes_limit where the backend reports one (TPU), else
    None (unknown — CPU test meshes have no meaningful ceiling, and
    fits() answers None rather than inventing one)."""
    import os

    env = os.environ.get("GOL_TPU_DEVICE_BUDGET_BYTES")
    if env:
        with contextlib.suppress(ValueError):
            return int(env)
    try:
        import jax

        limit = 0
        for d in jax.devices():
            ms = None
            with contextlib.suppress(Exception):
                ms = d.memory_stats()
            if not ms or "bytes_limit" not in ms:
                return None
            limit += int(ms["bytes_limit"])
        return limit or None
    except Exception:
        return None


#: Working-set multiple over one board's bytes: the scanned diff paths
#: keep the carry board, the new board and the stacked per-turn output
#: alive at once; 3x is the boards' own share (the diff STACK is priced
#: separately — it is chunk-bounded by DIFF_STACK_BUDGET already).
_BOARD_WORKING_SET = 3


def tile_ext_bytes(tile: int, halo_words: int = 1) -> int:
    """Device bytes of ONE resident macro-tile: the ghost-extended
    packed block the activity-driven stepper uploads per dispatch —
    (TILE/32 + 2g) word-rows by (TILE + 64g) lanes of uint32
    (parallel/tiled.py geometry). The ONE constant both `fits()`'s
    `resident_tiles` term and `max_resident_tiles` price, so the
    paging policy and the capacity answer cannot disagree."""
    if tile <= 0 or tile % 32 or halo_words < 1:
        raise ValueError(
            f"tile must be a positive multiple of 32 (got {tile}) "
            f"with halo_words >= 1 (got {halo_words})"
        )
    return (tile // 32 + 2 * halo_words) * (tile + 64 * halo_words) * 4


def max_resident_tiles(tile: int,
                       halo_words: int = 1) -> Optional[int]:
    """How many ghost-extended macro-tiles one device dispatch slab
    may hold: the budget over `tile_ext_bytes` times the same
    ~3x working-set multiple `fits()` charges per resident tile
    (upload slab + stepped result + transient). None when the backend
    reports no budget (the tiled stepper then falls back to its own
    conservative default) — never a guess."""
    budget = device_budget()
    if budget is None:
        return None
    return max(1, int(budget)
               // (tile_ext_bytes(tile, halo_words)
                   * _BOARD_WORKING_SET))


def fits(height: int, width: int, *, sessions: int = 1,
         packed: Optional[bool] = None,
         diff_stack_bytes: Optional[int] = None,
         resident_tiles: int = 0, tile: int = 0,
         tile_halo_words: int = 1) -> dict:
    """Will this geometry fit device memory — and how far can it grow?

    Pure arithmetic over the census and the board layout (never a
    device call): one packed board is H/32 * W * 4 bytes (the bitlife
    word layout), a dense one H * W; a bucket of S sessions stacks S of
    them; the working set holds ~3 boards' worth (carry + result +
    stacked diffs' board share) plus the engine's bounded diff-stack
    budget when the caller prices a watched run (`diff_stack_bytes`,
    e.g. engine.DIFF_STACK_BUDGET), plus — when the process also runs
    an activity-driven tiled stepper — `resident_tiles` ghost-extended
    macro-tile slots (`tile` names their side; `tile_ext_bytes` is the
    shared per-slot constant, charged at the same ~3x working-set
    multiple, so this answer and the tiled paging policy
    (`max_resident_tiles`) cannot disagree).

    Precedence of the budget deductions: the fixed side terms —
    `diff_stack_bytes`, then the resident-tile slab — come off the
    budget FIRST; `max_sessions` and `max_board_side` are answered
    from the remainder. (The budget itself follows `device_budget`:
    an explicit GOL_TPU_DEVICE_BUDGET_BYTES override wins over the
    allocator's bytes_limit.)

    Returns board_bytes / bucket_bytes / estimated working set,
    `budget_bytes` (None when the backend reports no ceiling — then
    `fits` is None, not a guess), the estimated `max_sessions` this
    geometry could stack before OOM, and `max_board_side` — the
    largest square single board the budget admits."""
    if height <= 0 or width <= 0 or sessions < 1:
        raise ValueError("need positive geometry and sessions >= 1")
    if resident_tiles < 0:
        raise ValueError("resident_tiles must be >= 0")
    if resident_tiles and not tile:
        raise ValueError(
            "resident_tiles needs tile= (the macro-tile side) to "
            "price a slot"
        )
    if packed is None:
        from gol_tpu.ops.bitlife import packable

        packed = packable(height, width)
    board = (height // 32) * width * 4 if packed else height * width
    bucket = board * sessions
    tile_bytes = (
        resident_tiles * tile_ext_bytes(tile, tile_halo_words)
        * _BOARD_WORKING_SET if resident_tiles else 0
    )
    side_terms = (diff_stack_bytes or 0) + tile_bytes
    need = bucket * _BOARD_WORKING_SET + side_terms
    budget = device_budget()
    out = {
        "height": height,
        "width": width,
        "sessions": sessions,
        "packed": bool(packed),
        "board_bytes": board,
        "bucket_bytes": bucket,
        "resident_tiles": resident_tiles,
        "resident_tile_bytes": tile_bytes,
        "working_set_bytes": need,
        "budget_bytes": budget,
        "fits": None,
        "max_sessions": None,
        "max_board_side": None,
    }
    if budget is None:
        return out
    usable = budget - side_terms
    out["fits"] = need <= budget
    out["headroom_bytes"] = budget - need
    if board > 0 and usable > 0:
        out["max_sessions"] = max(
            0, usable // (board * _BOARD_WORKING_SET)
        )
    # Largest square single board: bytes/cell is 1/8 packed (uint32
    # words of 32 cells), 1 dense; side rounded down to the packed
    # layout's 32-row granularity so the answer is actually buildable.
    per_cell = 0.125 if packed else 1.0
    if usable > 0:
        side = int((usable / (_BOARD_WORKING_SET * per_cell)) ** 0.5)
        out["max_board_side"] = side // 32 * 32 if packed else side
    return out


# --- dispatch split ------------------------------------------------------


def observe_split(enqueue_s: Optional[float] = None,
                  sync_s: Optional[float] = None,
                  host_s: Optional[float] = None) -> None:
    """Record one dispatch's device-vs-host time split. The phases are
    the boundaries the engine already crosses (no added realizations):
    `enqueue` = the dispatch call returning (host overhead to launch),
    `sync` = the fetched result materialising on host (device work +
    transfer — the block-until-ready boundary), `host` = decode +
    event fan-out. Fused chunks report enqueue only (nothing is
    fetched per chunk); diff chunks report all three, and the fraction
    gauge tracks the last fully-split dispatch."""
    if not _registry._ENABLED:
        return
    if enqueue_s is not None:
        _METRICS.split_seconds["enqueue"].observe(enqueue_s)
    if sync_s is not None:
        _METRICS.split_seconds["sync"].observe(sync_s)
    if host_s is not None:
        _METRICS.split_seconds["host"].observe(host_s)
    if enqueue_s is not None and sync_s is not None and host_s is not None:
        total = enqueue_s + sync_s + host_s
        if total > 0:
            _METRICS.device_fraction.set(round(sync_s / total, 5))


# --- bench snapshots -----------------------------------------------------


def plane_snapshot() -> dict:
    """The device plane's accumulated totals as one JSON-able dict —
    what bench.py embeds per lane (via `plane_delta`) and as the run
    total. Reads only registry handles and the census gauges."""
    compiles = {
        c: int(m.value) for c, m in _METRICS._compiles.items()
    }
    split = {
        p: {"count": h.count, "seconds": round(h.sum, 4)}
        for p, h in _METRICS.split_seconds.items()
    }
    return {
        "compiles": compiles,
        "compiles_total": sum(compiles.values()),
        "compile_seconds": round(_METRICS.compile_seconds.sum, 4),
        "split": split,
        "device_fraction": _METRICS.device_fraction.value,
        "live_buffers": int(_METRICS.live_buffers.value),
        "live_bytes": int(_METRICS.live_bytes.value),
        "hbm_watermark_bytes": int(_METRICS.watermark.value),
    }


def plane_delta(before: dict) -> dict:
    """What one bench lane did to the device plane: compile count/
    seconds and split deltas vs a `plane_snapshot()` taken before the
    lane, plus the current (peak-inclusive) census values."""
    now = plane_snapshot()
    out = {
        "compiles": now["compiles_total"] - before.get("compiles_total", 0),
        "compile_seconds": round(
            now["compile_seconds"] - before.get("compile_seconds", 0.0), 4
        ),
        "hbm_watermark_bytes": now["hbm_watermark_bytes"],
        "live_bytes": now["live_bytes"],
    }
    split = {}
    for p, v in now["split"].items():
        b = before.get("split", {}).get(p, {})
        dc = v["count"] - b.get("count", 0)
        ds = round(v["seconds"] - b.get("seconds", 0.0), 4)
        if dc:
            split[p] = {"count": dc, "seconds": ds}
    if split:
        out["split"] = split
    return out


# --- profiler driver (--profile-dir) -------------------------------------

_profile_dir: Optional[str] = None


def start_profile(directory: str) -> bool:
    """Start a `jax.profiler` capture into `directory` (the CLI's
    opt-in `--profile-dir`): the full XLA/device trace, linkable from
    Perfetto. The directory is recorded in the span tracer's export
    metadata so a merged report names the capture next to the
    host-side timeline. Registers an atexit stop so the capture is
    flushed even on unusual exits; returns False when the profiler is
    unavailable."""
    global _profile_dir
    if _profile_dir is not None:
        return True
    try:
        import atexit

        import jax

        jax.profiler.start_trace(directory)
    except Exception as e:
        flight.note("device.profile_failed", error=repr(e))
        return False
    _profile_dir = str(directory)
    tracing.set_metadata("profile_dir", _profile_dir)
    tracing.event("device.profile", "device", dir=_profile_dir)
    flight.note("device.profile", dir=_profile_dir)
    atexit.register(stop_profile)
    return True


def stop_profile() -> None:
    """Flush and stop the capture; idempotent."""
    global _profile_dir
    if _profile_dir is None:
        return
    _profile_dir = None
    with contextlib.suppress(Exception):
        import jax

        jax.profiler.stop_trace()
