"""Metrics registry — typed, low-overhead, process-global.

The runtime-visibility analog of the repo's trace layer (utils/trace.py
is the per-dispatch *trace*; this is the *metrics* plane — SURVEY §2's
gap read the other way: the reference had an unlocked ticker racing its
turn counter and no metrics at all; here every layer feeds a typed
registry that a live `/metrics` endpoint can expose).

Three metric types, Prometheus-shaped:

- `Counter`: monotone float, `inc(n)`.
- `Gauge`: last-write-wins float, `set/inc/dec`.
- `Histogram`: exponential (or caller-supplied) upper bounds, cumulative
  `le` semantics at exposition time, `observe(v)`.

Design constraints, in order:

- **Pure stdlib.** This module imports neither jax nor numpy nor any
  gol_tpu package: `analysis.invariants` (which must stay importable
  from worker processes and the linter CLI at zero cost) counts its
  violations here, so the registry has to sit below everything.
- **Never in a jitted path.** All instrumentation is host-side, at
  dispatch/event granularity (≤ kHz), never per cell or per traced op;
  `gol_tpu.analysis`'s `obs-in-jit` check enforces this statically.
- **Zero-cost when disabled.** `set_enabled(False)` (or
  `GOL_TPU_METRICS=0` in the environment) turns every `inc`/`set`/
  `observe` into an immediate return behind one module-global flag
  check; construction-time wrappers (parallel/stepper.py) additionally
  skip wrapping entirely when metrics are off at build time.
- **Thread-safe.** Writers are the engine thread, the ticker, conn
  writer threads and the broadcaster concurrently; every mutation takes
  the metric's own lock (uncontended at these rates), so totals are
  exact — pinned by tests/test_obs.py's concurrent-writer tests.

Identity: a metric is (name, labels). `Registry.counter(...)` et al.
are get-or-create — calling twice with the same identity returns the
same object, calling with the same name but a different type raises.
"""

from __future__ import annotations

import bisect
import contextlib
import json
import os
import tempfile
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "TopKGauge",
    "atomic_write_text",
    "counter",
    "enabled",
    "evict_entity",
    "exponential_buckets",
    "gauge",
    "histogram",
    "merge_cumulative_buckets",
    "quantile_from_buckets",
    "registry",
    "remove",
    "set_enabled",
    "track_entity_series",
]

#: Module-global enablement flag — ONE attribute read on every metric
#: mutation. Default on; `GOL_TPU_METRICS=0` (or set_enabled(False))
#: turns the whole plane off.
_ENABLED = os.environ.get("GOL_TPU_METRICS", "1") != "0"


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool = True) -> None:
    """Programmatic switch (tests, embedders). Affects mutation calls
    immediately; build-time gates (the stepper wrapper) read it at
    construction."""
    global _ENABLED
    _ENABLED = bool(on)


def atomic_write_text(path, text: str) -> None:
    """Crash-safe text write: temp file in the target directory, fsync,
    `os.replace` — a killed process never leaves a truncated artifact
    (the io/pgm.py discipline, shared here so Timeline dumps and
    registry dumps get it too)."""
    path = os.fspath(path)
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".obs-", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def exponential_buckets(start: float, factor: float, count: int) -> tuple:
    """`count` exponentially-spaced upper bounds from `start` —
    the Prometheus ExponentialBuckets shape."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    out, b = [], start
    for _ in range(count):
        out.append(b)
        b *= factor
    return tuple(out)


#: Default histogram bounds: 100 µs .. ~52 s, x2 — covers a single diff
#: dispatch on local hardware through a cold-compile-sized stall.
DEFAULT_BUCKETS = exponential_buckets(1e-4, 2.0, 20)

_LabelsKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Optional[Dict[str, str]]) -> _LabelsKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(key: _LabelsKey, extra: Sequence[Tuple[str, str]] = ()) -> str:
    pairs = list(key) + list(extra)
    if not pairs:
        return ""
    def esc(v: str) -> str:
        return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in pairs) + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    # Integral values print without the trailing .0 — easier to grep
    # and byte-stable across Python versions.
    return str(int(v)) if float(v).is_integer() and abs(v) < 1e15 else repr(v)


class _Metric:
    """Shared identity + lock; subclasses hold the value plane."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels: _LabelsKey):
        self.name = name
        self.help = help
        self.labels = labels
        self._lock = threading.Lock()

    # -- exposition --

    def sample_lines(self) -> Iterable[str]:
        raise NotImplementedError

    def snapshot_value(self):
        raise NotImplementedError


class Counter(_Metric):
    """Monotone counter. `inc(n)` with n >= 0."""

    kind = "counter"

    def __init__(self, name, help, labels):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not _ENABLED:
            return
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def sample_lines(self):
        yield f"{self.name}{_fmt_labels(self.labels)} {_fmt_value(self._value)}"

    def snapshot_value(self):
        return self._value


class Gauge(_Metric):
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def __init__(self, name, help, labels):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def sample_lines(self):
        yield f"{self.name}{_fmt_labels(self.labels)} {_fmt_value(self._value)}"

    def snapshot_value(self):
        return self._value


class Histogram(_Metric):
    """Distribution with fixed upper bounds (Prometheus cumulative-`le`
    semantics: an observation lands in the first bucket whose bound is
    >= v; exposition emits cumulative counts plus `_sum`/`_count`)."""

    kind = "histogram"

    def __init__(self, name, help, labels,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labels)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds
        # Per-bucket (non-cumulative) counts; index len(bounds) = +Inf.
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        if not _ENABLED:
            return
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def sample_lines(self):
        cum = 0
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        for bound, n in zip(self.bounds, counts):
            cum += n
            yield (f"{self.name}_bucket"
                   f"{_fmt_labels(self.labels, [('le', _fmt_value(bound))])}"
                   f" {cum}")
        yield (f"{self.name}_bucket"
               f"{_fmt_labels(self.labels, [('le', '+Inf')])} {total}")
        yield f"{self.name}_sum{_fmt_labels(self.labels)} {_fmt_value(s)}"
        yield f"{self.name}_count{_fmt_labels(self.labels)} {total}"

    def snapshot_value(self):
        with self._lock:
            return {
                "buckets": [[b, n] for b, n in
                            zip(list(self.bounds) + ["+Inf"], self._counts)],
                "sum": self._sum,
                "count": self._count,
            }

    def cumulative_buckets(self) -> list:
        """[(upper_bound, cumulative_count)] incl. the +Inf bucket —
        the exposition's `le` view, as data (quantile input)."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for b, n in zip(self.bounds, counts):
            cum += n
            out.append((b, cum))
        out.append((float("inf"), cum + counts[-1]))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Prometheus-style histogram_quantile over this histogram's
        own buckets (linear interpolation inside the landing bucket).
        None on an empty histogram."""
        return quantile_from_buckets(self.cumulative_buckets(), q)


class TopKGauge(_Metric):
    """Bounded-cardinality labeled gauge family — ONE registry entry
    whose exposition emits at most `cap` labeled children (the top-cap
    by value, the "worst" peers an operator actually wants named) plus
    a single `{label="other"}` aggregate (max over the rest, with an
    `<name>_other_children` companion so the hidden population is
    visible). Per-PEER labels at relay-scale peer counts would
    otherwise mint one registry child per connection: thousands of
    series per scrape for peers whose lag is 0. Children live in a
    plain dict — `set_child`/`remove_child` are O(1); ranking happens
    at exposition time only. The registry stays O(cap) on the wire and
    O(live children) in memory, and teardown (`remove_child`) keeps
    the dict bounded under churn (pinned by the 1000-peer test)."""

    kind = "gauge"

    def __init__(self, name, help, labels, label: str = "peer",
                 cap: int = 16):
        super().__init__(name, help, labels)
        if cap < 1:
            raise ValueError("cap must be >= 1")
        self.label = label
        self.cap = cap
        self._children: Dict[str, float] = {}

    def set_child(self, child, v: float) -> None:
        if not _ENABLED:
            return
        with self._lock:
            self._children[str(child)] = float(v)

    def remove_child(self, child) -> bool:
        with self._lock:
            return self._children.pop(str(child), None) is not None

    def child_count(self) -> int:
        return len(self._children)

    def _ranked(self):
        with self._lock:
            items = list(self._children.items())
        items.sort(key=lambda kv: (-kv[1], kv[0]))
        return items[: self.cap], items[self.cap:]

    def sample_lines(self):
        top, rest = self._ranked()
        for k, v in sorted(top):
            yield (f"{self.name}"
                   f"{_fmt_labels(self.labels, [(self.label, k)])}"
                   f" {_fmt_value(v)}")
        if rest:
            other = max(v for _, v in rest)
            yield (f"{self.name}"
                   f"{_fmt_labels(self.labels, [(self.label, 'other')])}"
                   f" {_fmt_value(other)}")
            yield (f"{self.name}_other_children"
                   f"{_fmt_labels(self.labels)} {len(rest)}")

    def snapshot_value(self):
        top, rest = self._ranked()
        out = {"children": dict(top)}
        if rest:
            out["other"] = max(v for _, v in rest)
            out["other_children"] = len(rest)
        return out


def quantile_from_buckets(buckets, q: float) -> Optional[float]:
    """`histogram_quantile` over cumulative `le` buckets: `buckets` is
    [(upper_bound, cumulative_count), ...] sorted by bound, +Inf last
    (exactly `Histogram.cumulative_buckets()`, or what a scraper
    reassembles from `<name>_bucket{le=...}` series — the ONE shared
    quantile the console, the bench capture and the tests all use, so
    the numbers cannot drift between surfaces).

    Prometheus semantics: the target rank is q * total observations;
    the answer interpolates linearly inside the first bucket whose
    cumulative count reaches it (lower edge 0 for the first bucket). A
    rank landing in the +Inf bucket returns the highest finite bound —
    the histogram cannot resolve beyond it. None on an empty histogram;
    q outside [0, 1] raises."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_cum = 0.0, 0
    saw_finite = False
    for bound, cum in buckets:
        if bound == float("inf"):
            break
        saw_finite = True
        if cum >= rank:
            frac = (0.0 if cum == prev_cum
                    else (rank - prev_cum) / (cum - prev_cum))
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = bound, cum
    # Rank lands in the +Inf bucket: the highest finite bound is the
    # most the histogram can resolve (Prometheus does the same).
    return prev_bound if saw_finite else None


def merge_cumulative_buckets(bucket_lists) -> list:
    """Sum several cumulative-bucket lists (same-name histograms from
    N registries/endpoints or N label sets) into one — fleet-wide
    percentiles. Bounds need not match: the union grid is used, each
    input contributing its cumulative count at every bound at or past
    its own (cumulative counts are monotone step functions, so the sum
    at a bound between two of an input's bounds is the lower one —
    exact, no interpolation)."""
    lists = [b for b in bucket_lists if b]
    if not lists:
        return []
    bounds = sorted({b for lst in lists for b, _ in lst})
    out = []
    for bound in bounds:
        cum = 0
        for lst in lists:
            at = 0
            for b, c in lst:
                if b <= bound:
                    at = c
                else:
                    break
            cum += at
        out.append((bound, cum))
    if not out or out[-1][0] != float("inf"):
        out.append((float("inf"), sum(lst[-1][1] for lst in lists)))
    return out


class Registry:
    """Get-or-create metric store with Prometheus-text and JSON
    exposition. One process-global instance (`REGISTRY`) serves the
    whole package; tests build private ones."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[Tuple[str, _LabelsKey], _Metric]" = {}
        #: Per-entity series declarations (bounded-cardinality audit):
        #: label -> family names whose per-entity children must leave
        #: the registry with the entity. Plain families mint one
        #: labeled series per entity ({label: value}); topk families
        #: are single TopKGauge entries whose CHILDREN are keyed by the
        #: entity. `evict_entity` is the one teardown path every churny
        #: plane (sessions, peers, usage principals) routes through —
        #: pinned by the 1000-tenant churn test.
        self._entity_plain: "Dict[str, set]" = {}
        self._entity_topk: "Dict[str, set]" = {}

    def _get_or_create(self, cls, name, help, labels, **kw):
        key = (name, _labels_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help, key[1], **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[dict] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[dict] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[dict] = None,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def topk_gauge(self, name: str, help: str = "",
                   labels: Optional[dict] = None, *,
                   label: str = "peer", cap: int = 16) -> TopKGauge:
        """Bounded per-entity gauge family (see TopKGauge): exposition
        cardinality is O(cap) however many children are live."""
        return self._get_or_create(TopKGauge, name, help, labels,
                                   label=label, cap=cap)

    def get(self, name: str, labels: Optional[dict] = None
            ) -> Optional[_Metric]:
        """The registered metric under one identity, or None — a peek
        that never creates (evict_entity and tests use it)."""
        with self._lock:
            return self._metrics.get((name, _labels_key(labels)))

    def track_entity_series(self, label: str, *names: str,
                            topk: bool = False) -> None:
        """Declare per-entity metric families: every series of `names`
        keyed by `{label: <entity>}` (or, with topk=True, every
        TopKGauge child keyed by the entity) is evicted by ONE
        `evict_entity(label, entity)` call at teardown. Idempotent;
        declaration order is free (a family may be tracked before it
        is ever registered)."""
        with self._lock:
            dst = self._entity_topk if topk else self._entity_plain
            dst.setdefault(label, set()).update(names)

    def evict_entity(self, label: str, value) -> int:
        """Remove every tracked per-entity series of one entity — the
        shared bounded-cardinality teardown (sessions at destroy/park,
        peers at disconnect, usage principals at forget). Returns the
        number of series/children actually removed; unknown entities
        are a harmless 0."""
        with self._lock:
            plain = tuple(self._entity_plain.get(label, ()))
            topk = tuple(self._entity_topk.get(label, ()))
        n = 0
        for name in plain:
            if self.remove(name, {label: str(value)}):
                n += 1
        for name in topk:
            m = self.get(name)
            if isinstance(m, TopKGauge) and m.remove_child(value):
                n += 1
        return n

    def remove(self, name: str, labels: Optional[dict] = None) -> bool:
        """Evict one labeled series (e.g. a destroyed session's child
        metrics — gol_tpu.sessions). Bounded-cardinality discipline:
        per-ENTITY labels are legal only if the entity's teardown calls
        this, otherwise the registry grows without bound under churn.
        Returns False when the series was never registered. A handle
        obtained earlier keeps working but lands nowhere visible; the
        next get-or-create under the same identity starts fresh."""
        key = (name, _labels_key(labels))
        with self._lock:
            return self._metrics.pop(key, None) is not None

    def metrics(self) -> list:
        with self._lock:
            return list(self._metrics.values())

    def percentiles(self, name: str, qs: Sequence[float] = (0.5, 0.95, 0.99)
                    ) -> Optional[dict]:
        """{p50: v, p95: v, ...} over EVERY labeled series of the named
        histogram family merged into one distribution (an endpoint's
        per-label children are one population to an operator). None
        when the family is absent or empty."""
        lists = [m.cumulative_buckets() for m in self.metrics()
                 if m.name == name and isinstance(m, Histogram)]
        if not lists:
            return None
        merged = merge_cumulative_buckets(lists)
        out = {}
        for q in qs:
            v = quantile_from_buckets(merged, q)
            if v is None:
                return None
            out[f"p{q * 100:g}"] = round(v, 6)
        return out

    # -- exposition --

    def prometheus_text(self) -> str:
        """The text exposition format (one HELP/TYPE header per metric
        family, then every labeled series)."""
        lines = []
        seen_headers = set()
        for m in sorted(self.metrics(), key=lambda m: (m.name, m.labels)):
            if m.name not in seen_headers:
                seen_headers.add(m.name)
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.sample_lines())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able {series: {type, value}} map — the `/vars` payload
        and the BENCH_DETAIL.json capture. Series keys carry their
        labels in Prometheus spelling so the two expositions line up."""
        out = {}
        for m in sorted(self.metrics(), key=lambda m: (m.name, m.labels)):
            key = f"{m.name}{_fmt_labels(m.labels)}"
            out[key] = {"type": m.kind, "value": m.snapshot_value()}
            if m.help:
                out[key]["help"] = m.help
        return out

    def dump(self, path) -> None:
        """Crash-safe JSON snapshot (temp file + rename — a killed
        engine never leaves a truncated artifact)."""
        atomic_write_text(path, json.dumps(self.snapshot(), indent=2))


#: The process-global registry every gol_tpu layer instruments into.
REGISTRY = Registry()


def registry() -> Registry:
    return REGISTRY


def counter(name: str, help: str = "", labels: Optional[dict] = None) -> Counter:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Optional[dict] = None) -> Gauge:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Optional[dict] = None,
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labels, buckets)


def remove(name: str, labels: Optional[dict] = None) -> bool:
    return REGISTRY.remove(name, labels)


def track_entity_series(label: str, *names: str, topk: bool = False) -> None:
    REGISTRY.track_entity_series(label, *names, topk=topk)


def evict_entity(label: str, value) -> int:
    return REGISTRY.evict_entity(label, value)
