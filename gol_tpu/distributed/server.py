"""Engine server — the TPU-side half of the distributed split.

The reference spec's topology is controller ⇄ engine over the network,
with the engine running headless "on AWS" and controllers attaching and
detaching at will (ref: README.md:157-233; the committed code has only
dead stubs, ref: gol/distributor.go:44-52,459-530). This server is that
capability, working:

- owns the Engine (device turn loop) and keeps it evolving whether or
  not a controller is attached — the fault story's first half
  (SURVEY.md §5: "engine keeps evolving without a controller");
- accepts ONE controller at a time over TCP; on attach it syncs the
  full board (the role of the commented GetCurrentBoard RPC,
  ref: gol/distributor.go:489-498) and then streams events;
- per-turn CellFlipped diffs are streamed only while a controller that
  asked for them is attached (`hello.want_flips`) — flips-off engines
  run the chunked fast path, so a detached engine pays zero event tax;
- verbs: 'p'/'s' forwarded to the engine; 'q' detaches the controller
  and the engine lives on (ref: README.md:182); 'k' shuts the whole
  system down after a final snapshot (ref: README.md:183);
- `resume_from` boots the engine from an out/<W>x<H>x<T>.pgm snapshot,
  continuing at turn T — PGM-out + PGM-in checkpoint/resume
  (SURVEY.md §5).
"""

from __future__ import annotations

import contextlib
import hmac
import itertools
import logging
import queue
import socket
import struct
import threading
from typing import Optional

import numpy as np

from gol_tpu.checkpoint import snapshot_turn
from gol_tpu.distributed import wire
from gol_tpu.engine.distributor import Engine
from gol_tpu.events import (
    BoardSync,
    CellFlipped,
    FinalTurnComplete,
    FlipBatch,
    TurnComplete,
)
from gol_tpu.io.pgm import read_pgm
from gol_tpu.params import Params

__all__ = ["EngineServer", "snapshot_turn"]

log = logging.getLogger(__name__)


class _Conn:
    """One attached controller: socket + send lock + subscription mode."""

    _next_token = itertools.count(1).__next__  # only the accept thread draws

    def __init__(self, sock: socket.socket, want_flips: bool,
                 compact: bool = False, binary: bool = False,
                 levels: bool = False):
        self.sock = sock
        # Send-side timeout only (SO_SNDTIMEO, not settimeout: the read
        # side must keep blocking forever — controllers send verbs
        # rarely). A stalled-but-open controller (SIGSTOP, dead network
        # path) fills its TCP window and would otherwise block the
        # broadcaster's sendall forever, wedging the whole event path;
        # after 30s of no progress the send raises and the controller
        # is detached like any dead peer.
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_SNDTIMEO,
            struct.pack("ll", 30, 0),
        )
        self.want_flips = want_flips
        #: Peer advertised the zlib'd-int32 flips encoding in its hello;
        #: older controllers get legacy JSON pair lists (the skew the
        #: serve/connect split exists for runs both ways).
        self.compact = compact
        #: Peer advertised raw binary frames (tag + header + zlib) for
        #: the bulk plane — flips, board syncs, final alive sets ride
        #: without the base64-inside-JSON inflation (~33% on a
        #: link-bound watched run, VERDICT r4 Weak #4).
        self.binary = binary
        #: Peer can apply per-cell gray levels (multi-state batches,
        #: r5). Without it, level batches downgrade to plain flips —
        #: a pre-r5 peer must keep receiving frames it understands
        #: rather than ignorable unknown tags (a silently frozen
        #: display).
        self.levels = levels
        #: Matches this connection to the BoardSync it requested.
        self.token = _Conn._next_token()
        # No events flow until this connection's BoardSync has been sent:
        # a controller's first message is always the board state, never a
        # TurnComplete it has no context for.
        self.synced = False
        self._lock = threading.Lock()

    def send(self, msg: dict) -> None:
        with self._lock:
            wire.send_msg(self.sock, msg)

    def send_raw(self, payload: bytes) -> None:
        with self._lock:
            wire.send_frame(self.sock, payload)

    def close(self) -> None:
        with contextlib.suppress(OSError):
            self.sock.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self.sock.close()


class EngineServer:
    """Serve one engine run to at-most-one controller at a time."""

    def __init__(
        self,
        params: Params,
        host: str = "127.0.0.1",
        port: int = 8030,
        *,
        resume_from: Optional[str] = None,
        secret: Optional[str] = None,
        **engine_kwargs,
    ):
        self.params = params
        #: Shared-secret attach token. When set, a hello whose "secret"
        #: does not match is rejected and logged — the board state and
        #: the 'k' kill verb are not for any peer that can reach the
        #: port (the reference's open :8030 listener,
        #: ref: gol/distributor.go:49-52, is a flaw to beat, not match).
        self._secret = secret
        if resume_from is not None:
            engine_kwargs.setdefault("initial_world", read_pgm(resume_from))
            engine_kwargs.setdefault("start_turn", snapshot_turn(resume_from))
        self._keys: queue.Queue = queue.Queue()
        # Flips ride as per-turn FlipBatch arrays: the broadcaster and
        # the wire consume them vectorized — per-cell Python event
        # objects capped the whole watched pipeline at ~30 turns/s.
        self.engine = Engine(
            params, keypresses=self._keys, emit_flips=False,
            emit_flip_batches=True, **engine_kwargs
        )
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()
        self._conn: Optional[_Conn] = None
        self._conn_lock = threading.Lock()
        self._shutdown = threading.Event()
        self.done = threading.Event()
        self._threads: list[threading.Thread] = []

    # --- lifecycle ---

    def start(self) -> "EngineServer":
        self.engine.start()
        for fn, name in [(self._accept_loop, "gol-accept"),
                         (self._broadcast_loop, "gol-broadcast")]:
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self, *, stop_engine: bool = True) -> None:
        if self._shutdown.is_set():
            return
        self._shutdown.set()
        if stop_engine:
            self.engine.stop()
        with contextlib.suppress(OSError):
            self._listener.close()
        with self._conn_lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            with contextlib.suppress(Exception):
                conn.send({"t": "bye"})
            conn.close()
        self.engine.join(timeout=60)
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    # --- accept path ---

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return  # listener closed
            try:
                # Control-only receive: an unauthenticated peer must
                # never make the server inflate a bulk zlib payload.
                hello = wire.recv_msg(sock, allow_binary=False)
                if not hello or hello.get("t") != "hello":
                    raise wire.WireError(f"bad hello: {hello!r}")
            except (wire.WireError, OSError, ValueError) as e:
                log.warning("rejecting connection from %s: %s", addr, e)
                sock.close()
                continue

            # Compare as UTF-8 bytes: compare_digest on str raises
            # TypeError for non-ASCII input, and the secret here is
            # attacker-controlled — a unicode probe must be a clean
            # rejection, not a dead accept thread.
            if self._secret is not None and not hmac.compare_digest(
                str(hello.get("secret", "")).encode("utf-8", "replace"),
                self._secret.encode("utf-8", "replace"),
            ):
                log.warning(
                    "rejecting unauthenticated attach from %s", addr
                )
                with contextlib.suppress(Exception):
                    wire.send_msg(
                        sock, {"t": "error", "reason": "unauthorized"}
                    )
                sock.close()
                continue

            conn = _Conn(sock, bool(hello.get("want_flips", False)),
                         compact=bool(hello.get("compact", False)),
                         binary=bool(hello.get("binary", False)),
                         levels=bool(hello.get("levels", False)))
            with self._conn_lock:
                if self._conn is not None:
                    busy = True
                else:
                    self._conn, busy = conn, False
            if busy:
                # One controller at a time (the reference's controller is
                # singular too, ref: README.md:201-207).
                with contextlib.suppress(Exception):
                    wire.send_msg(sock, {"t": "error", "reason": "busy"})
                sock.close()
                continue

            # Immediate ack: the controller's handshake timeout covers
            # the first reply, and the BoardSync only arrives once the
            # engine services the attach between dispatches — on a cold
            # TPU that can be a 40s compile away. The ack lands within
            # ms so attaches never time out behind a dispatch (clients
            # ignore unknown message kinds, so old ones are unaffected).
            try:
                conn.send({"t": "attach-ack"})
            except (wire.WireError, OSError):
                self._detach(conn)
                continue
            self._attach(conn)
            threading.Thread(
                target=self._reader_loop, args=(conn,),
                name="gol-conn-reader", daemon=True,
            ).start()

    def _attach(self, conn: _Conn) -> None:
        """Ask the engine to publish a BoardSync (and, if wanted, start
        per-turn flips) at its next dispatch boundary. Both ride the
        event stream, so the broadcaster delivers them in turn order —
        no side-channel race between the sync and newer diffs.

        Per-turn TurnComplete events flow whenever ANY controller is
        attached (flips or not — a headless controller still follows
        progress, ref: sdl/loop.go:44-47 prints per-event); a detached
        engine emits none and runs full-size fused chunks."""
        self.engine.emit_turns = True
        self.engine.request_board_sync(
            enable_flips=conn.want_flips, token=conn.token
        )

    def _release(self, conn: _Conn) -> None:
        """Free the controller slot (without closing the socket)."""
        with self._conn_lock:
            if self._conn is conn:
                self._conn = None
                self.engine.emit_flips = False
                self.engine.emit_turns = False

    def _detach(self, conn: _Conn) -> None:
        self._release(conn)
        conn.close()

    def _refresh_flips(self) -> None:
        """Re-derive engine.emit_flips/emit_turns from the currently
        attached connection, atomically against attach/detach — the
        single writer discipline that keeps broadcaster-side corrections
        from racing a concurrent _detach or a fresh attach."""
        with self._conn_lock:
            cur = self._conn
            self.engine.emit_flips = cur is not None and cur.want_flips
            self.engine.emit_turns = cur is not None

    # --- controller → engine ---

    def _reader_loop(self, conn: _Conn) -> None:
        while True:
            try:
                # Controllers only ever send JSON control messages.
                msg = wire.recv_msg(conn.sock, allow_binary=False)
            except (wire.WireError, OSError):
                msg = None
            if msg is None:  # controller went away (crash or close)
                self._detach(conn)
                return
            if msg.get("t") != "key":
                continue
            key = msg.get("key")
            if key in ("p", "s"):
                self._keys.put(key)
            elif key == "q":
                # Detach only — the engine keeps evolving
                # (ref: README.md:182). The slot is freed BEFORE the
                # ack: a controller that reattaches the moment
                # `detach()` returns must never bounce off its own
                # stale registration ("busy" race, seen under load).
                self._release(conn)
                with contextlib.suppress(Exception):
                    conn.send({"t": "detached"})
                conn.close()
                return
            elif key == "k":
                # Global shutdown with a final snapshot (ref: README.md:183).
                self._keys.put("k")
                return  # broadcaster sends the tail + bye, then shutdown

    # --- engine → controller ---

    def _broadcast_loop(self) -> None:
        """Single consumer of the engine's event stream; each turn's
        flips become one wire message — from a FlipBatch array directly
        (the engine's vectorized form) or by batching a CellFlipped
        burst (engines injected with the per-cell contract)."""
        flips: "list | object" = []
        flips_levels = None  # (N,) gray levels of a multi-state batch
        flips_turn = 0
        for ev in self.engine.events:
            conn = self._conn
            if isinstance(ev, FlipBatch):
                if conn is not None and conn.want_flips and len(ev.cells):
                    flips_turn = ev.completed_turns
                    flips = ev.cells
                    flips_levels = getattr(ev, "levels", None)
                continue
            if isinstance(ev, CellFlipped):
                if conn is not None and conn.want_flips:
                    flips_turn = ev.completed_turns
                    if not isinstance(flips, list):
                        flips = []
                    flips.append([ev.cell.x, ev.cell.y])
                continue
            if conn is None:
                flips = []
                flips_levels = None
                if isinstance(ev, BoardSync):
                    # Sync requested by a controller that vanished: drop
                    # the stale enable_flips so a detached engine pays
                    # zero diff tax (re-derived under the lock — a new
                    # controller may have just attached).
                    self._refresh_flips()
                continue
            try:
                if isinstance(ev, BoardSync):
                    if ev.token != conn.token:
                        # Sync for a controller that vanished before it
                        # was serviced; re-derive the subscription from
                        # the *current* connection (by want_flips alone —
                        # its own sync may still be queued behind this
                        # one, so keying off synced would freeze it).
                        self._refresh_flips()
                        continue
                    flips = []  # the sync supersedes any batched diff
                    flips_levels = None
                    if conn.binary:
                        conn.send_raw(wire.board_to_frame(
                            ev.completed_turns, ev.world, ev.token
                        ))
                    else:
                        conn.send(wire.board_to_msg(
                            ev.completed_turns, ev.world, ev.token
                        ))
                    conn.synced = True
                    continue
                if not conn.synced:
                    continue  # pre-sync events are not this controller's
                if len(flips) and isinstance(ev, TurnComplete):
                    # Levels ride only to peers that advertised them.
                    lv = flips_levels if conn.levels else None
                    if conn.binary:
                        conn.send_raw(
                            wire.level_flips_to_frame(flips_turn, flips, lv)
                            if lv is not None
                            else wire.flips_to_frame(flips_turn, flips)
                        )
                    elif conn.compact:
                        conn.send(wire.flips_to_msg(
                            flips_turn, flips, levels=lv
                        ))
                    else:
                        # Legacy JSON peers are two-state; levels are
                        # dropped (they could not apply them anyway).
                        conn.send({"t": "flips", "turn": flips_turn,
                                   "cells": np.asarray(flips).tolist()})
                    flips = []
                    flips_levels = None
                if conn.binary and isinstance(ev, FinalTurnComplete):
                    conn.send_raw(wire.final_to_frame(
                        ev.completed_turns, ev.alive
                    ))
                else:
                    conn.send(wire.event_to_msg(ev))
            except (wire.WireError, OSError):
                self._detach(conn)
                flips = []
                flips_levels = None
                continue
        # Engine stream closed: the run is over (final turn, 'k', or stop).
        with self._conn_lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            with contextlib.suppress(Exception):
                conn.send({"t": "bye"})
            conn.close()
        self.shutdown(stop_engine=False)
